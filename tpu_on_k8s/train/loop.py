"""Zero-stall step-driving loop: bounded async dispatch, device-resident
metrics, non-blocking checkpoints, stalled-step watchdog.

Every caller used to hand-roll ``for i in range(steps): state, m = step(...);
float(m["loss"])`` — that ``float()`` is a host round-trip *per step*, which
serializes dispatch with device compute: the host cannot enqueue step N+1
until step N's result has crossed PCIe. ``TrainLoop`` inverts the contract:

* **Metrics stay device-resident.** The loop holds them as in-flight device
  arrays and transfers to host only every ``log_every`` steps — one transfer
  per window, at most ⌈steps/log_every⌉ over a run. Because a host transfer
  of step N's metrics waits (in program order) for steps 1..N, the window
  sync is also the window's timing barrier.

* **Dispatch is bounded, not unbounded.** Fire-and-forget dispatch with no
  backpressure can run the host arbitrarily far ahead (donated buffers and
  the dispatch queue grow with it); the loop waits on the oldest in-flight
  step — a dispatch-queue wait, *not* a host transfer — once more than
  ``max_inflight`` steps are unsynced.

* **Checkpoints are enqueued, not awaited.** ``checkpoint_every`` saves go
  through orbax's async path (``wait=False``); the loop drains with
  ``wait_until_finished`` only at exit and on preemption notice
  (``preemption_signal`` → final save + drain + clean stop), so a save's
  serialization cost overlaps subsequent steps instead of stalling them.

* **Hangs become events.** A dead chip or wedged collective used to present
  as a silent forever-hang in ``float(...)``. The watchdog thread watches
  sync progress; past ``stall_timeout`` seconds without any, it emits one
  structured ``stalled_step`` event (log line + ``on_stall`` callback +
  ``TrainMetrics`` counter) per stall episode — the orchestration plane's
  failover machinery gets a signal instead of a mystery.

The loop is step-shape agnostic: ``step_fn(state, batch) -> (state,
metrics)`` covers the LM ``Trainer`` and (via a tuple-unpacking adapter) the
vision ``ClassifierTrainer``; ``batches`` is any iterator — typically
``data.prefetch.device_prefetch`` over the native ``DataLoader`` so H2D of
batch N+1 overlaps step N, completing the pipeline: disk → host queue → HBM
→ compute, with the host thread only ever *scheduling*.
"""
from __future__ import annotations

import collections
import contextlib
import logging
import os
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax

from tpu_on_k8s import chaos
from tpu_on_k8s.api import constants
from tpu_on_k8s.obs.trace import ensure as ensure_tracer
from tpu_on_k8s.utils import profiling
from tpu_on_k8s.utils.logging import get_logger, kv

log = get_logger("train.loop")


def _host_sync(tree: Any) -> Dict[str, Any]:
    """THE host-transfer point — one device→host copy of a metrics pytree.
    ``jax.device_get`` waits for the real values (unlike
    ``block_until_ready`` on relay-backed platforms, a transfer cannot
    return early), so this is both the sync and the progress proof. Module
    level so tests can count transfers by wrapping it."""
    host = jax.device_get(tree)
    return {k: (float(v) if getattr(v, "size", None) == 1 else v)
            for k, v in host.items()}


def _device_wait(tree: Any) -> None:
    """Bound the dispatch queue without a host transfer: wait for the
    oldest in-flight step's buffers to exist on device. Module level so
    tests can observe the backpressure path. Caveat: on relay-backed dev
    images where ``block_until_ready`` returns before execution finishes
    (see bench.py), this bound is advisory and the watchdog heartbeat it
    feeds is optimistic — set ``stall_timeout`` comfortably above a full
    window's wall time there; on conforming backends (CPU, real TPU) it is
    exact."""
    jax.block_until_ready(tree)


@dataclass
class LoopResult:
    """What a ``TrainLoop.run`` returns: final state plus the run's
    bookkeeping (every host-synced metrics window, in order)."""

    state: Any
    history: List[Tuple[int, Dict[str, Any]]] = field(default_factory=list)
    steps: int = 0
    host_syncs: int = 0
    checkpoints_enqueued: int = 0
    checkpoint_failures: int = 0
    seconds: float = 0.0
    preempted: bool = False
    reshards: int = 0
    reshard_fallback: bool = False

    @property
    def last_metrics(self) -> Dict[str, Any]:
        return self.history[-1][1] if self.history else {}


class TrainLoop:
    """Drive ``step_fn`` over ``batches`` with bounded async dispatch.

    Args:
      step_fn: ``(state, batch) -> (state, metrics)`` — e.g.
        ``Trainer.train_step`` (metrics must be a dict of device scalars).
      state: initial (sharded) train state; donated through each step.
      batches: iterator/iterable of device-ready batches (pair with
        ``device_prefetch`` so H2D overlaps compute).
      log_every: steps per host sync window. The ONLY host transfers the
        loop performs happen at window boundaries (and the final partial
        window): ⌈steps/log_every⌉ total.
      max_inflight: cap on unsynced dispatched steps (default
        ``2*log_every``); enforced with a device wait, not a host transfer.
      checkpoint_manager / checkpoint_every / generation: enqueue
        ``manager.save(state, step=..., generation=..., wait=False)`` every
        N steps; drained at exit and on preemption.
      preemption_signal: polled once per step; returning True triggers
        final save + drain + clean stop (``LoopResult.preempted``).
      reshard_signal: the live-rescale sibling of ``preemption_signal``
        (`tpu_on_k8s/parallel/reshard.py`): polled once per step; a
        returned ``ReshardNotice`` makes the loop drain its window and
        pending saves, transform params + optimizer state onto the
        notice's (mesh, rules), swap in the rebuilt (AOT-warmed) step
        program, and CONTINUE counting global steps — the run never
        exits. A transform that fails before its one donating dispatch
        (validation, injected ``ReshardAbort``) leaves state intact; the
        loop counts the fallback and exits via the preemption path so
        the orchestrator's checkpoint-restart rescale takes over.
      reshard_metrics: optional ``ReshardMetrics`` — reshards/fallback
        counters, bytes-moved counter, transform-seconds gauge.
      on_metrics: ``(step, metrics_dict, step_seconds)`` per sync window.
      on_stall / stall_timeout: watchdog — with ``stall_timeout > 0`` a
        daemon thread emits one structured stall event per episode when no
        sync progress happens for that long.
      metrics: optional ``TrainMetrics`` — step-time/tokens-per-sec/MFU
        gauges and sync/stall counters, fed at each window.
      tokens_per_step / flops_per_step / peak_flops: throughput/MFU gauge
        inputs (``flops_per_step`` from ``compile.train_step_flops``).
      tracer: optional ``obs.Tracer`` — one ``train.window`` span per
        host-sync window (step range, loss, step time).
      accountant: optional ``obs.account.TrainingAccountant`` — the
        goodput ledger: each sync window reports its (novel vs
        replayed) step time, and the run's close attributes the
        residual wall time as preempt/overhead waste; the accountant
        publishes the ``TrainMetrics`` ``goodput_fraction`` gauge. An
        orchestrator that restarts a preempted job calls
        ``accountant.resume(checkpoint_step)`` between incarnations so
        re-executed steps count as replay waste, not progress.
      profile_dir / profiler_port / annotate_steps: the
        `utils/profiling.py` hooks — capture an XLA trace of the run
        into ``profile_dir``, serve the live profiler on
        ``profiler_port``, and wrap each dispatched step in a named
        ``train.step`` TraceAnnotation so the XLA timeline is
        attributable. Defaults come from the ``TPU_ON_K8S_PROFILE_DIR``
        / ``TPU_ON_K8S_PROFILER_PORT`` env the operator's
        ``--profile-dir``/``--profiler-port`` flags inject into slice
        pods; unset (the default) is behavior-neutral.
    """

    def __init__(self, step_fn: Callable[[Any, Any], Tuple[Any, Dict]],
                 state: Any, batches: Iterable, *,
                 log_every: int = 10,
                 max_inflight: Optional[int] = None,
                 checkpoint_manager: Any = None,
                 checkpoint_every: int = 0,
                 generation: int = 0,
                 preemption_signal: Optional[Callable[[], bool]] = None,
                 reshard_signal: Optional[Callable[[], Optional[Any]]] = None,
                 reshard_metrics: Any = None,
                 on_metrics: Optional[Callable[[int, Dict, float], None]] = None,
                 on_stall: Optional[Callable[[Dict], None]] = None,
                 stall_timeout: float = 0.0,
                 metrics: Any = None,
                 tokens_per_step: int = 0,
                 flops_per_step: float = 0.0,
                 peak_flops: float = 0.0,
                 tracer: Any = None,
                 accountant: Any = None,
                 profile_dir: Optional[str] = None,
                 profiler_port: Optional[int] = None,
                 annotate_steps: Optional[bool] = None):
        if log_every < 1:
            raise ValueError(f"log_every must be >= 1, got {log_every}")
        self.step_fn = step_fn
        self.state = state
        self.batches = batches
        self.log_every = log_every
        self.max_inflight = (2 * log_every if max_inflight is None
                             else max_inflight)
        if self.max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {self.max_inflight}")
        self.checkpoint_manager = checkpoint_manager
        self.checkpoint_every = checkpoint_every
        self.generation = generation
        self.preemption_signal = preemption_signal
        self.reshard_signal = reshard_signal
        self.reshard_metrics = reshard_metrics
        self.on_metrics = on_metrics
        self.on_stall = on_stall
        self.stall_timeout = stall_timeout
        self.metrics = metrics
        self.tokens_per_step = tokens_per_step
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops
        # observability: one ``train.window`` span per host-sync window
        # (`tpu_on_k8s/obs/trace.py`); the per-step XLA-timeline bridge
        # is `utils/profiling.annotate` below, not host-side spans — a
        # span per dispatched step would put host work on the zero-stall
        # hot path the loop exists to keep empty
        self._tracer = ensure_tracer(tracer)
        self._window_span: Any = None
        # goodput ledger (`tpu_on_k8s/obs/account.py`): fed from the
        # quantities the loop already measures — no new clock reads on
        # the hot path, and None is a strict no-op
        self.accountant = accountant
        # profiling hooks (`tpu_on_k8s/utils/profiling.py`), previously
        # dead code: the operator's ``--profile-dir``/``--profiler-port``
        # flags inject ENV_PROFILE_DIR / ENV_PROFILER_PORT into slice
        # pods (`controller/tpujob.py` _inject_perf_env), and the loop —
        # the one code path every production trainer drives — reads them
        # here, so XLA trace capture needs no per-caller plumbing.
        if profile_dir is None:
            profile_dir = os.environ.get(constants.ENV_PROFILE_DIR) or None
        if profiler_port is None:
            raw = os.environ.get(constants.ENV_PROFILER_PORT, "")
            profiler_port = int(raw) if raw.strip().isdigit() else None
        self.profile_dir = profile_dir
        self.profiler_port = profiler_port or None
        # step annotation rides along whenever a trace is captured (the
        # named regions are what make the XLA timeline attributable);
        # explicit True forces it for an externally-started trace
        self.annotate_steps = (annotate_steps if annotate_steps is not None
                               else profile_dir is not None)
        self._profiler_started = False

        self._should_stop = False
        self._running = False
        self._inflight = 0
        self._dispatched = 0
        self._heartbeat = time.perf_counter()
        self._stall_latched = False
        self._watchdog_stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    # ------------------------------------------------------------- control
    def stop(self) -> None:
        """Request a clean stop before the next dispatch (callback/signal
        safe). Treated like a preemption notice: final save + drain."""
        self._should_stop = True

    # ------------------------------------------------------------ watchdog
    def _touch(self) -> None:
        self._heartbeat = time.perf_counter()
        self._stall_latched = False

    def _watchdog_run(self) -> None:
        poll = max(min(self.stall_timeout / 4.0, 1.0), 0.01)
        while not self._watchdog_stop.wait(poll):
            if not self._running or self._stall_latched:
                continue
            gap = time.perf_counter() - self._heartbeat
            if gap <= self.stall_timeout:
                continue
            # one event per stall episode: latch until the next heartbeat
            self._stall_latched = True
            event = {"event": "stalled_step",
                     "step": self._dispatched,
                     "inflight": self._inflight,
                     "seconds_since_progress": round(gap, 3),
                     "stall_timeout": self.stall_timeout}
            kv(log, logging.WARNING, "stalled_step", **event)
            if self.metrics is not None:
                self.metrics.inc("stalled_steps")
            if self.on_stall is not None:
                self.on_stall(event)

    # ----------------------------------------------------------- profiling
    @contextlib.contextmanager
    def _profiling_session(self):
        """Activate the `utils/profiling.py` hooks for one ``run``: the
        live profiler server (bound once per loop, ever) and XLA trace
        capture into ``profile_dir``. Either hook failing degrades to a
        warning — profiling must never take down training — and with
        neither configured this is a pass-through."""
        if self.profiler_port is not None and not self._profiler_started:
            self._profiler_started = True
            try:
                profiling.start_server(self.profiler_port)
            except Exception as e:  # noqa: BLE001 — port taken, no backend
                if self.metrics is not None:
                    self.metrics.inc("profiling_failures")
                warnings.warn(f"profiler server on :{self.profiler_port} "
                              f"unavailable: {e}")
        if self.profile_dir is None:
            yield
            return
        capture = contextlib.ExitStack()
        try:
            capture.enter_context(profiling.trace(self.profile_dir))
        except Exception as e:  # noqa: BLE001
            if self.metrics is not None:
                self.metrics.inc("profiling_failures")
            warnings.warn(f"XLA trace capture into {self.profile_dir} "
                          f"unavailable: {e}")
        try:
            yield
        finally:
            # the trace WRITES at stop — a full disk here must not eat a
            # run whose every training step succeeded
            try:
                capture.close()
            except Exception as e:  # noqa: BLE001
                if self.metrics is not None:
                    self.metrics.inc("profiling_failures")
                warnings.warn(f"XLA trace capture into {self.profile_dir} "
                              f"failed to finalize: {e}")

    def _annotate_step(self):
        """Per-dispatch XLA-timeline region (``train.step``): the bridge
        that makes a captured trace attributable to loop steps. A plain
        nullcontext when annotation is off — nothing on the hot path."""
        return (profiling.annotate("train.step") if self.annotate_steps
                else contextlib.nullcontext())

    # ----------------------------------------------------------------- run
    def run(self, steps: int) -> LoopResult:
        """Drive ``steps`` training steps; returns the :class:`LoopResult`.
        Host syncs happen only at ``log_every`` windows (+ the final
        partial window); checkpoints drain before returning."""
        result = LoopResult(state=self.state)
        pending: collections.deque = collections.deque()
        batches = iter(self.batches)
        self._running = True
        self._touch()
        t0 = time.perf_counter()
        t_window = t0
        hooks = contextlib.ExitStack()
        try:
            hooks.enter_context(self._profiling_session())
            for i in range(1, steps + 1):
                # the chaos site is a second preemption source: a scheduled
                # PreemptNotice lands exactly like a SIGTERM-handler flag
                if self._should_stop or (self.preemption_signal is not None
                                         and self.preemption_signal()) or (
                        chaos.fire(chaos.SITE_TRAIN_PREEMPT, step=i)
                        is not None):
                    result.preempted = True
                    break
                if self.reshard_signal is not None:
                    notice = self.reshard_signal()
                    if notice is not None:
                        t_window = self._do_reshard(result, pending, notice,
                                                    t_window)
                        if result.reshard_fallback:
                            # transform aborted before its donating
                            # dispatch: state is the intact source —
                            # exit via the preemption path (final save +
                            # drain) and let the orchestrator's
                            # checkpoint-restart rescale take over
                            result.preempted = True
                            break
                try:
                    batch = next(batches)
                except StopIteration:
                    break
                step_fault = chaos.fire(chaos.SITE_TRAIN_STEP, step=i)
                if step_fault is not None:
                    raise step_fault.to_exception()
                if self._window_span is None:
                    # one span per host-sync window, closed by
                    # _sync_window — per-step host spans would put work
                    # on the zero-stall path; the XLA timeline carries
                    # the per-step story via _annotate_step
                    self._window_span = self._tracer.start(
                        "train.window", start_step=i)
                with self._annotate_step():
                    self.state, step_metrics = self.step_fn(self.state,
                                                            batch)
                pending.append(step_metrics)
                self._dispatched = result.steps = i
                self._inflight = len(pending)
                # a returned dispatch is host progress; on a hung device
                # dispatches stop within max_inflight steps (backpressure or
                # the window sync blocks), so staleness still detects it
                self._touch()
                if i == 1 and self.stall_timeout > 0:
                    # arm the watchdog only once the first dispatch has
                    # returned: a lazily-jitted first step legitimately
                    # spends minutes in trace+compile, which must not read
                    # as a stall (AOT warmup via train/compile.py makes
                    # this instant)
                    self._watchdog_stop.clear()
                    self._watchdog = threading.Thread(
                        target=self._watchdog_run,
                        name="trainloop-watchdog", daemon=True)
                    self._watchdog.start()
                # backpressure: a device wait on the oldest unsynced step,
                # NOT a host transfer — the sync cadence is unaffected
                while len(pending) > self.max_inflight:
                    _device_wait(pending.popleft())
                    self._inflight = len(pending)
                    self._touch()
                if self.checkpoint_every and i % self.checkpoint_every == 0:
                    self._enqueue_save(result, i)
                if i % self.log_every == 0 or i == steps:
                    t_window = self._sync_window(result, pending, i, t_window)

            # still inside the watchdog's watch: the exit path can hang in
            # exactly the ways the loop body can (a wedged collective under
            # the partial-window sync, a stuck checkpoint drain) and must
            # surface as stall events too, not die silently
            if pending:
                # an early exit (preemption / stop / data end) leaves a
                # partial window in flight: surface it before saving
                self._sync_window(result, pending, result.steps, t_window)
            if result.preempted and self.checkpoint_manager is not None:
                # preemption notice: persist the exact stopping point, then
                # drain — the restarted pod resumes here with a warm
                # compile cache instead of replaying the window
                self._enqueue_save(result, result.steps)
            if self.checkpoint_manager is not None:
                try:
                    self.checkpoint_manager.wait_until_finished()
                except Exception as e:  # noqa: BLE001 — async save failed
                    # an async save that failed in the background surfaces
                    # here; the training that happened since is still real —
                    # record the failure, keep the state we computed
                    result.checkpoint_failures += 1
                    kv(log, logging.WARNING, "checkpoint_drain_failed",
                       error=f"{type(e).__name__}: {e}")
                    if self.metrics is not None:
                        self.metrics.inc("checkpoint_failures")
        finally:
            hooks.close()
            if self._window_span is not None:
                # an aborted run (chaos StepFailure, preemption between
                # dispatch and sync) leaves a window open — close it so
                # the dump shows where training stopped
                self._window_span.finish("aborted")
                self._window_span = None
            self._running = False
            if self._watchdog is not None:
                self._watchdog_stop.set()
                self._watchdog.join(timeout=5.0)
                self._watchdog = None
        result.state = self.state
        result.seconds = time.perf_counter() - t0
        if self.accountant is not None:
            # close the goodput ledger for this run: wall time the
            # windows didn't account (compile, checkpoint drains, the
            # preemption save) is waste, attributed by how the run ended
            self.accountant.run_complete(result.seconds,
                                         preempted=result.preempted)
        return result

    # ------------------------------------------------------------- reshard
    def _do_reshard(self, result: LoopResult, pending: collections.deque,
                    notice: Any, t_window: float) -> float:
        """Apply one live-reshard notice: drain the in-flight window and
        pending saves, transform state + step program, continue. Returns
        the new window clock (the pause is accounted as ``reshard``
        waste, never charged to the next window's step time). On a
        failed transform sets ``result.reshard_fallback`` — state is the
        intact source (the transform's only mutating step is one atomic
        donating dispatch, and every failure path fires before it)."""
        if pending:
            # surface the partial window first: pre-reshard steps are
            # attributed at pre-reshard cadence
            t_window = self._sync_window(result, pending, result.steps,
                                         t_window)
        if self.checkpoint_manager is not None:
            # a save writing the OLD layout must finish before the
            # buffers it references are donated away
            try:
                self.checkpoint_manager.wait_until_finished()
            except Exception as e:  # noqa: BLE001 — same as the exit drain
                result.checkpoint_failures += 1
                kv(log, logging.WARNING, "checkpoint_drain_failed",
                   error=f"{type(e).__name__}: {e}")
                if self.metrics is not None:
                    self.metrics.inc("checkpoint_failures")
        span = self._tracer.start("train.reshard", step=result.steps,
                                  **({"tag": notice.tag}
                                     if getattr(notice, "tag", "") else {}))
        # the reshard pause is hardware wall time — the measured datum
        # itself, like the loop's step timing
        t0 = time.perf_counter()  # analyze: allow[determinism] hardware pause timing is the datum
        try:
            new_state, new_step, plan = notice.apply(self.state, self.step_fn)
        except Exception as e:  # noqa: BLE001 — fall back, never corrupt
            result.reshard_fallback = True
            if self.reshard_metrics is not None:
                self.reshard_metrics.inc("reshard_fallbacks")
            kv(log, logging.WARNING, "reshard_fallback", step=result.steps,
               error=f"{type(e).__name__}: {e}")
            span.set(error=f"{type(e).__name__}: {e}")
            span.finish("aborted")
            self._notify_reshard(notice, "on_failed")
            return t_window
        # analyze: allow[determinism] hardware pause timing (see above)
        dt = time.perf_counter() - t0
        self.state = new_state
        self.step_fn = new_step
        if getattr(notice, "generation", None) is not None:
            # subsequent checkpoints land in the rescale's generation
            self.generation = notice.generation
        result.reshards += 1
        if self.accountant is not None:
            # attributed DISTINCTLY: a live-reshard pause is neither a
            # restart nor a preemption (obs/account.py "reshard" bucket)
            self.accountant.pause("reshard", dt)
        if self.reshard_metrics is not None:
            self.reshard_metrics.inc("reshards")
            self.reshard_metrics.inc("bytes_moved", plan.bytes_moved)
            self.reshard_metrics.set_gauge("transform_seconds", dt)
        span.set(bytes_moved=plan.bytes_moved, leaves_moved=plan.n_moved,
                 seconds=round(dt, 6))
        span.finish()
        kv(log, logging.INFO, "reshard", step=result.steps,
           plan=plan.describe(), seconds=round(dt, 3))
        self._notify_reshard(notice, "on_applied")
        self._touch()
        # analyze: allow[determinism] window clock reset after the pause
        return time.perf_counter()

    def _notify_reshard(self, notice: Any, hook: str) -> None:
        """Fire a notice's ack callback (``on_applied``/``on_failed``).
        The acks are control-plane writes (the ReshardAgent patches the
        completion annotation): a transient API failure there must not
        kill a run whose transform already reached its outcome — warned
        AND counted, never silent, never fatal."""
        cb = getattr(notice, hook, None)
        if cb is None:
            return
        try:
            cb()
        except Exception as e:  # noqa: BLE001 — the run outlives its ack
            if self.reshard_metrics is not None:
                self.reshard_metrics.inc("reshard_ack_failures")
            kv(log, logging.WARNING, "reshard_ack_failed", hook=hook,
               error=f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------- windows
    def _sync_window(self, result: LoopResult, pending: collections.deque,
                     step: int, t_window: float) -> float:
        """One host transfer for the whole window: the last step's metrics
        (program order on the device makes it wait for every prior step).
        Earlier steps are drained with device waits first so each completed
        step feeds the watchdog heartbeat — a long healthy window must not
        read as a stall; only a step that never completes does."""
        if self.metrics is not None:
            # unsynced dispatch depth at window close (the gauge's scrape
            # cadence is coarser than a step, so the window edge is the
            # meaningful sample point)
            self.metrics.set_gauge("steps_inflight", float(len(pending)))
        last = pending.pop()
        while pending:
            _device_wait(pending.popleft())
            self._inflight = len(pending) + 1
            self._touch()
        self._inflight = 0
        host = _host_sync(last)
        self._touch()
        now = time.perf_counter()
        result.host_syncs += 1
        window_steps = max(step - (result.history[-1][0]
                                   if result.history else 0), 1)
        step_seconds = (now - t_window) / window_steps
        result.history.append((step, host))
        loss = host.get("loss")
        kv(log, logging.INFO, "train_window", step=step,
           loss=(round(loss, 4) if isinstance(loss, float) else loss),
           step_ms=round(step_seconds * 1e3, 1))
        if self._window_span is not None:
            self._window_span.set(
                step=step, steps=window_steps,
                step_seconds=round(step_seconds, 6),
                **({"loss": round(loss, 6)}
                   if isinstance(loss, float) else {}))
            self._window_span.finish()
            self._window_span = None
        if self.accountant is not None:
            # novel steps are productive, re-executed ones (a resume
            # replaying past the last checkpoint) are replay waste —
            # the accountant tells them apart by the global step
            self.accountant.window(step, window_steps, step_seconds)
        if self.metrics is not None:
            m = self.metrics
            m.inc("host_syncs")
            m.set_gauge("step_seconds", step_seconds)
            if self.tokens_per_step:
                m.set_gauge("tokens_per_sec",
                            self.tokens_per_step / step_seconds)
            if self.flops_per_step and self.peak_flops:
                m.set_gauge("mfu", self.flops_per_step / step_seconds
                            / self.peak_flops)
        if self.on_metrics is not None:
            self.on_metrics(step, host, step_seconds)
        return now

    # --------------------------------------------------------- checkpoints
    def _enqueue_save(self, result: LoopResult, step: int) -> None:
        """Enqueue an async save. A FAILING save (full disk, revoked
        credentials, injected ``SaveFailure``) must not kill the run —
        training state is intact and the next cadence save gets a fresh
        chance; resume falls back to the last checkpoint that did land
        (the chaos soak proves the fallback reproduces the trajectory)."""
        try:
            fault = chaos.fire(chaos.SITE_TRAIN_SAVE, step=step)
            if fault is not None:
                raise fault.to_exception()
            self.checkpoint_manager.save(self.state, step=step,
                                         generation=self.generation,
                                         wait=False)
        except Exception as e:  # noqa: BLE001 — saves are best-effort
            result.checkpoint_failures += 1
            kv(log, logging.WARNING, "checkpoint_save_failed", step=step,
               error=f"{type(e).__name__}: {e}")
            if self.metrics is not None:
                self.metrics.inc("checkpoint_failures")
            return
        result.checkpoints_enqueued += 1
        if self.metrics is not None:
            self.metrics.inc("checkpoints_enqueued")
