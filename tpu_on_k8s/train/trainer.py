"""Sharded training step: the compute-plane "train()" path.

The whole step — forward, loss, backward, optimizer update — is one jitted
function over a ``jax.sharding.Mesh``. Gradient reductions across ``data`` /
``fsdp`` and activation collectives across ``model`` are *not* written here:
parameter and batch shardings carry the information and XLA's SPMD partitioner
inserts psum / all-gather / reduce-scatter on ICI (scaling-book recipe).

Optimizer state inherits parameter shardings for free: the partition rules in
`tpu_on_k8s/parallel/partition.py` use ``re.search`` on the '/'-joined path,
and optax's Adam moments (``.../mu/<param path>``, ``.../nu/<param path>``)
contain the parameter path as a suffix — so mu/nu land exactly where their
parameter lives, and scalars (step counts) fall back to replication.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from tpu_on_k8s.parallel.mesh import (
    batch_sharding,
    put_global,
    put_process_local,
)
from tpu_on_k8s.parallel.partition import PartitionRule, named_sharding
from tpu_on_k8s.parallel.ring import ring_context


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray            # scalar int32
    params: Any
    opt_state: Any


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean next-token CE. logits [B, L, V] fp32; targets [B, L] int.

    Formulated as ``logsumexp - gold`` rather than ``-log_softmax[target]``:
    identical math, but avoids materialising a second [B, L, V] fp32 tensor
    (the log-probabilities) in HBM — the lse reduction and the gold-logit
    gather both read the logits once.
    """
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - gold
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def chunked_cross_entropy(feats: jnp.ndarray, head: jnp.ndarray,
                          targets: jnp.ndarray, n_chunks: int = 8,
                          mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean next-token CE without ever materialising [B, L, V] logits.

    feats [B, L, D] (post-final-norm hidden states, from
    ``Transformer.apply(..., method="features")``), head [D, V], targets
    [B, L], optional mask [B, L] (1 = count the token — same semantics as
    ``cross_entropy_loss``). Tokens are processed in ``n_chunks`` sequence
    chunks under ``jax.lax.scan`` + ``jax.checkpoint``: each chunk computes
    its logits, reduces to (lse - gold), and discards them; backward
    recomputes per chunk. Peak HBM for the loss drops from O(B·L·V) to
    O(B·L·V / n_chunks) at the cost of one extra head matmul in backward.
    """
    b, l, d = feats.shape
    n = b * l
    if n % n_chunks != 0:
        raise ValueError(f"B*L={n} not divisible by n_chunks={n_chunks}")
    chunk = n // n_chunks
    fl = feats.reshape(n_chunks, chunk, d)
    tg = targets.reshape(n_chunks, chunk)
    mk = (jnp.ones((n_chunks, chunk), jnp.float32) if mask is None
          else mask.reshape(n_chunks, chunk).astype(jnp.float32))

    @jax.checkpoint
    def body(carry, xs):
        f, t, m = xs
        logits = jnp.dot(f, head, preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, t[:, None], axis=-1)[:, 0]
        return carry + jnp.sum((lse - gold) * m), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (fl, tg, mk))
    denom = n if mask is None else jnp.maximum(jnp.sum(mk), 1.0)
    return total / denom


def _scale_by_adam_lp(b1: float, b2: float, eps: float,
                      mu_dtype, nu_dtype) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` with independently reducible moment dtypes.

    optax exposes ``mu_dtype`` only; this adds ``nu_dtype``. Both moments
    are *accumulated* in fp32 (cast up, EMA, cast back down) so the only
    loss is storage precision — bf16 keeps ~2.4 significant digits, plenty
    for a variance that only feeds an rsqrt. Halving nu cuts 2·|params|
    bytes of optimizer-state HBM traffic per step."""

    def init(params):
        mu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32),
                                      mu=mu, nu=nu)

    def update(updates, state, params=None):
        del params
        f32 = jnp.float32
        mu = jax.tree.map(
            lambda m, g: (b1 * m.astype(f32)
                          + (1 - b1) * g.astype(f32)).astype(mu_dtype or g.dtype),
            state.mu, updates)
        nu = jax.tree.map(
            lambda n, g: (b2 * n.astype(f32)
                          + (1 - b2) * jnp.square(g.astype(f32))
                          ).astype(nu_dtype or g.dtype),
            state.nu, updates)
        count = state.count + 1
        bc1 = 1 - b1 ** count.astype(f32)
        bc2 = 1 - b2 ** count.astype(f32)
        out = jax.tree.map(
            lambda m, n, g: ((m.astype(f32) / bc1)
                             / (jnp.sqrt(n.astype(f32) / bc2) + eps)
                             ).astype(g.dtype),
            mu, nu, updates)
        return out, optax.ScaleByAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      decay_steps: int = 10000,
                      max_grad_norm: float = 1.0,
                      mu_dtype=None, nu_dtype=None) -> optax.GradientTransformation:
    """AdamW + clip + warmup-cosine. ``mu_dtype=jnp.bfloat16`` halves the
    first-moment HBM footprint/traffic (~+1% step rate at 350M on v5e); the
    variance stays fp32 for stability unless ``nu_dtype`` is also lowered
    (bf16 nu is accumulated in fp32 and stored bf16 — see
    ``_scale_by_adam_lp``)."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(decay_steps, warmup_steps + 1))
    if nu_dtype is not None:
        adam = optax.chain(
            _scale_by_adam_lp(0.9, 0.95, 1e-8, mu_dtype, nu_dtype),
            optax.add_decayed_weights(weight_decay),
            optax.scale_by_learning_rate(sched),
        )
    else:
        adam = optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
                           mu_dtype=mu_dtype)
    return optax.chain(optax.clip_by_global_norm(max_grad_norm), adam)


def make_sharded_init(model: Any, optimizer: optax.GradientTransformation,
                      mesh: Mesh, rules: Sequence[PartitionRule],
                      example_tokens: jnp.ndarray) -> Callable[[jax.Array], TrainState]:
    """Returns init(rng) → TrainState materialised *directly sharded* on the
    mesh (out_shardings on the jitted initializer — no host-side full copy)."""

    def init(rng: jax.Array) -> TrainState:
        params = model.init(rng, example_tokens)["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    abstract = jax.eval_shape(init, jax.random.key(0))
    # named_sharding also validates divisibility: a bad rule fails loudly
    # here at setup, not as an XLA error inside the jitted init.
    shardings = named_sharding(abstract, mesh, rules)
    return jax.jit(init, out_shardings=shardings)


def packed_positions_and_segments(tokens: jnp.ndarray, eos_id: int
                                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(positions, segments) for stream-packed windows (EOS-separated
    documents, `tpu_on_k8s/data/packing.py::pack_stream`).

    A token's segment = number of EOS separators strictly before it (the
    EOS closes its own document), and its position RESTARTS at each
    segment — with the block-diagonal attention mask this makes packed
    training numerically identical to running each document alone
    (positions and visible context both match the standalone run)."""
    eq = (tokens == eos_id).astype(jnp.int32)
    segments = jnp.cumsum(eq, axis=1) - eq
    idx = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
    # start of a token's segment = (last EOS index before it) + 1, via an
    # exclusive running max of (i+1)·[token_i is EOS]
    marks = (idx + 1) * eq
    cmax = jax.lax.cummax(marks, axis=1)
    starts = jnp.concatenate(
        [jnp.zeros_like(cmax[:, :1]), cmax[:, :-1]], axis=1)
    return idx - starts, segments


def packed_loss_mask(tokens: jnp.ndarray, eos_id: int) -> jnp.ndarray:
    """[B, L] mask over the shifted next-token targets of a stream-packed
    ``tokens [B, L+1]``: a position counts only when its input and target
    share a segment. Cross-document boundaries (an EOS "predicting" the
    first token of an unrelated shuffled document) are unlearnable noise,
    and EOS-padded tails (``pack_greedy``) pair consecutive EOS tokens in
    DIFFERENT segments — both mask to zero, so padding-heavy windows no
    longer report systematically lower loss."""
    _, seg = packed_positions_and_segments(tokens, eos_id)
    return (seg[:, :-1] == seg[:, 1:]).astype(jnp.float32)


def _make_loss_fn(model: Any, aux_loss_weight: float, loss_chunks: int,
                  segment_eos: Optional[int] = None):
    """(params, tokens [B, L+1]) → (objective, aux) — shared by the train
    and eval steps so the two can never compute different losses.
    ``segment_eos``: treat batches as stream-packed windows (per-document
    attention isolation + restarted positions)."""

    def loss_fn(params: Any, tokens: jnp.ndarray):
        inputs = tokens[:, :-1]
        positions = segments = loss_mask = None
        if segment_eos is not None:
            positions, segments = packed_positions_and_segments(
                inputs, segment_eos)
            loss_mask = packed_loss_mask(tokens, segment_eos)
        mutable = ["losses"] if aux_loss_weight else False
        if loss_chunks:
            out = model.apply({"params": params}, inputs, positions,
                              segments, method="features",
                              mutable=mutable)
            (feats, head), losses = out if aux_loss_weight else (out, {})
            ce = chunked_cross_entropy(feats, head, tokens[:, 1:],
                                       loss_chunks, mask=loss_mask)
        else:
            out = model.apply({"params": params}, inputs, positions,
                              segments, mutable=mutable)
            logits, losses = out if aux_loss_weight else (out, {})
            ce = cross_entropy_loss(logits, tokens[:, 1:],
                                    mask=loss_mask)
        aux = (sum(jnp.sum(leaf)
                   for leaf in jax.tree.leaves(dict(losses).get("losses", {})))
               if aux_loss_weight else jnp.zeros((), jnp.float32))
        # weight = how many targets the mean covered — gradient
        # accumulation must weight microbatch means by it or masked
        # (packed) microbatches with few counted targets get over-weighted
        weight = (jnp.sum(loss_mask) if loss_mask is not None
                  else jnp.asarray(float(inputs.shape[0]
                                         * inputs.shape[1]), jnp.float32))
        return ce + aux_loss_weight * aux, (aux, weight)

    return loss_fn


def make_eval_step(model: Any, aux_loss_weight: float = 0.0,
                   loss_chunks: int = 0, segment_eos: Optional[int] = None
                   ) -> Callable[[Any, jnp.ndarray], dict]:
    """Forward-only evaluation on a [B, L+1] token batch: the same
    objective as ``make_train_step`` (shared loss fn), no gradients, no
    state mutation. Returns {"loss", "perplexity", "aux_loss"}."""
    loss_fn = _make_loss_fn(model, aux_loss_weight, loss_chunks,
                            segment_eos)

    def step(params: Any, tokens: jnp.ndarray) -> dict:
        loss, (aux, _) = loss_fn(params, tokens)
        # perplexity is exp(CROSS-ENTROPY); the objective folds the aux
        # penalty in, so back it out (loss = ce + w·aux)
        return {"loss": loss,
                "perplexity": jnp.exp(loss - aux_loss_weight * aux),
                "aux_loss": aux}

    return jax.jit(step)


def make_train_step(model: Any, optimizer: optax.GradientTransformation,
                    aux_loss_weight: float = 0.0, loss_chunks: int = 0,
                    grad_accum: int = 1,
                    segment_eos: Optional[int] = None,
                    ) -> Callable[[TrainState, jnp.ndarray], Tuple[TrainState, dict]]:
    """One language-model train step on a [B, L] token batch (next-token CE,
    internal shift). Donates the state buffers. jit shardings propagate from
    the inputs, so the same compiled step serves any mesh.

    ``aux_loss_weight`` > 0 collects the model's ``losses`` collection (MoE
    load-balance terms, `tpu_on_k8s/models/moe.py`) into the objective.
    ``loss_chunks`` > 0 uses the chunked head+CE path (requires the model to
    expose ``features``; see ``chunked_cross_entropy``).
    ``grad_accum`` > 1 splits the batch into that many equal microbatches
    under ``lax.scan``, accumulating target-weighted gradient sums in fp32
    before ONE optimizer update — the effective batch grows without the
    activation memory, and the objective equals the full-batch mean
    exactly (up to summation order) even when a packed loss mask leaves
    microbatches with different counted-target counts.
    """

    loss_fn = _make_loss_fn(model, aux_loss_weight, loss_chunks,
                            segment_eos)

    def grads_and_loss(params: Any, tokens: jnp.ndarray):
        if grad_accum <= 1:
            (loss, (aux, _)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, tokens)
            return (loss, aux), grads
        b = tokens.shape[0]
        if b % grad_accum:
            raise ValueError(
                f"batch {b} not divisible by grad_accum {grad_accum}")
        micro = tokens.reshape(grad_accum, b // grad_accum, tokens.shape[1])

        def body(carry, mb):
            gsum, lsum, asum, wsum = carry
            (loss, (aux, w)), g = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            gsum = jax.tree.map(
                lambda s, x: s + w * x.astype(jnp.float32), gsum, g)
            return (gsum, lsum + w * loss, asum + w * aux, wsum + w), None

        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             params)
        z = jnp.zeros((), jnp.float32)
        (gsum, lsum, asum, wsum), _ = jax.lax.scan(
            body, (zeros, z, z, z), micro)
        wsum = jnp.maximum(wsum, 1.0)
        grads = jax.tree.map(lambda g, p: (g / wsum).astype(p.dtype),
                             gsum, params)
        return (lsum / wsum, asum / wsum), grads

    def step(state: TrainState, tokens: jnp.ndarray) -> Tuple[TrainState, dict]:
        (loss, aux), grads = grads_and_loss(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss,
                   "aux_loss": aux,
                   "grad_norm": optax.global_norm(grads),
                   "step": state.step}
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), metrics

    return jax.jit(step, donate_argnums=(0,))


class Trainer:
    """Convenience wrapper tying model, optimizer, mesh and rules together.

    The orchestration plane launches one Trainer per slice host; all hosts
    execute the same jitted step (SPMD), with jax.distributed initialisation
    handled by the pod env the TPUJob reconciler injected
    (`tpu_on_k8s/controller/tpujob.py`).
    """

    def __init__(self, model: Any, rules: Sequence[PartitionRule],
                 mesh: Mesh,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 aux_loss_weight: float = 0.0, loss_chunks: int = 0,
                 grad_accum: int = 1,
                 segment_eos: Optional[int] = None):
        self.model = model
        self.rules = list(rules)
        self.mesh = mesh
        self.optimizer = optimizer or default_optimizer()
        self._step = make_train_step(self.model, self.optimizer,
                                     aux_loss_weight, loss_chunks,
                                     grad_accum, segment_eos)
        self._eval = make_eval_step(self.model, aux_loss_weight,
                                    loss_chunks, segment_eos)
        self._init_cache = {}

    def init_state(self, rng: jax.Array, example_tokens: jnp.ndarray) -> TrainState:
        key = (example_tokens.shape, str(example_tokens.dtype))
        if key not in self._init_cache:
            self._init_cache[key] = make_sharded_init(
                self.model, self.optimizer, self.mesh, self.rules,
                example_tokens)
        with ring_context(self.mesh):
            return self._init_cache[key](rng)

    def shard_batch(self, tokens: jnp.ndarray) -> jnp.ndarray:
        # put_global handles multi-process meshes (each slice host
        # contributes its addressable shards of the SAME full array)
        return put_global(tokens, batch_sharding(self.mesh, tokens.shape))

    def shard_local_batch(self, tokens_local) -> jnp.ndarray:
        """Global sharded batch from each host's DISJOINT loader shard
        ([per_host, L] rows — ``DataLoader(shard_id=process_id)``); the
        global batch is per_host × process_count. Using ``shard_batch``
        here would silently treat one host's shard as the whole batch."""
        global_shape = ((tokens_local.shape[0] * jax.process_count(),)
                        + tuple(tokens_local.shape[1:]))
        return put_process_local(tokens_local,
                                 batch_sharding(self.mesh, global_shape),
                                 global_shape)

    def train_step(self, state: TrainState, tokens: jnp.ndarray):
        # ring_context makes the mesh ambient while jit traces, so
        # attn_impl="ring" models can build their seq-axis shard_map.
        with ring_context(self.mesh):
            return self._step(state, tokens)

    def eval_step(self, state: TrainState, tokens: jnp.ndarray) -> dict:
        """Forward-only loss/perplexity on a held-out batch — the same
        objective the train step optimizes, no state change."""
        with ring_context(self.mesh):
            return self._eval(state.params, tokens)

    def fit(self, state: TrainState, batches, steps: int, **loop_kwargs):
        """Drive ``steps`` training steps through the zero-stall
        ``TrainLoop`` (device-resident metrics, bounded async dispatch,
        non-blocking checkpoints — `tpu_on_k8s/train/loop.py`). ``batches``
        is an iterator of device-ready token batches (pair with
        ``data.prefetch.device_prefetch``). Returns a ``LoopResult``."""
        from tpu_on_k8s.train.loop import TrainLoop

        return TrainLoop(self.train_step, state, batches,
                         **loop_kwargs).run(steps)
