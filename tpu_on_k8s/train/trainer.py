"""Sharded training step: the compute-plane "train()" path.

The whole step — forward, loss, backward, optimizer update — is one jitted
function over a ``jax.sharding.Mesh``. Gradient reductions across ``data`` /
``fsdp`` and activation collectives across ``model`` are *not* written here:
parameter and batch shardings carry the information and XLA's SPMD partitioner
inserts psum / all-gather / reduce-scatter on ICI (scaling-book recipe).

Optimizer state inherits parameter shardings for free: the partition rules in
`tpu_on_k8s/parallel/partition.py` use ``re.search`` on the '/'-joined path,
and optax's Adam moments (``.../mu/<param path>``, ``.../nu/<param path>``)
contain the parameter path as a suffix — so mu/nu land exactly where their
parameter lives, and scalars (step counts) fall back to replication.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from tpu_on_k8s.parallel.mesh import batch_sharding
from tpu_on_k8s.parallel.partition import PartitionRule, named_sharding
from tpu_on_k8s.parallel.ring import ring_context


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray            # scalar int32
    params: Any
    opt_state: Any


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean next-token CE. logits [B, L, V] fp32; targets [B, L] int."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      decay_steps: int = 10000,
                      max_grad_norm: float = 1.0,
                      mu_dtype=None) -> optax.GradientTransformation:
    """AdamW + clip + warmup-cosine. ``mu_dtype=jnp.bfloat16`` halves the
    first-moment HBM footprint/traffic (~+1% step rate at 350M on v5e); the
    variance stays fp32 for stability."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(decay_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def make_sharded_init(model: Any, optimizer: optax.GradientTransformation,
                      mesh: Mesh, rules: Sequence[PartitionRule],
                      example_tokens: jnp.ndarray) -> Callable[[jax.Array], TrainState]:
    """Returns init(rng) → TrainState materialised *directly sharded* on the
    mesh (out_shardings on the jitted initializer — no host-side full copy)."""

    def init(rng: jax.Array) -> TrainState:
        params = model.init(rng, example_tokens)["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    abstract = jax.eval_shape(init, jax.random.key(0))
    # named_sharding also validates divisibility: a bad rule fails loudly
    # here at setup, not as an XLA error inside the jitted init.
    shardings = named_sharding(abstract, mesh, rules)
    return jax.jit(init, out_shardings=shardings)


def make_train_step(model: Any, optimizer: optax.GradientTransformation,
                    aux_loss_weight: float = 0.0,
                    ) -> Callable[[TrainState, jnp.ndarray], Tuple[TrainState, dict]]:
    """One language-model train step on a [B, L] token batch (next-token CE,
    internal shift). Donates the state buffers. jit shardings propagate from
    the inputs, so the same compiled step serves any mesh.

    ``aux_loss_weight`` > 0 collects the model's ``losses`` collection (MoE
    load-balance terms, `tpu_on_k8s/models/moe.py`) into the objective.
    """

    def loss_fn(params: Any, tokens: jnp.ndarray):
        if aux_loss_weight:
            logits, out = model.apply({"params": params}, tokens[:, :-1],
                                      mutable=["losses"])
            aux = sum(jnp.sum(leaf)
                      for leaf in jax.tree.leaves(out.get("losses", {})))
        else:
            logits = model.apply({"params": params}, tokens[:, :-1])
            aux = jnp.zeros((), jnp.float32)
        ce = cross_entropy_loss(logits, tokens[:, 1:])
        return ce + aux_loss_weight * aux, aux

    def step(state: TrainState, tokens: jnp.ndarray) -> Tuple[TrainState, dict]:
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss,
                   "aux_loss": aux,
                   "grad_norm": optax.global_norm(grads),
                   "step": state.step}
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), metrics

    return jax.jit(step, donate_argnums=(0,))


class Trainer:
    """Convenience wrapper tying model, optimizer, mesh and rules together.

    The orchestration plane launches one Trainer per slice host; all hosts
    execute the same jitted step (SPMD), with jax.distributed initialisation
    handled by the pod env the TPUJob reconciler injected
    (`tpu_on_k8s/controller/tpujob.py`).
    """

    def __init__(self, model: Any, rules: Sequence[PartitionRule],
                 mesh: Mesh,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 aux_loss_weight: float = 0.0):
        self.model = model
        self.rules = list(rules)
        self.mesh = mesh
        self.optimizer = optimizer or default_optimizer()
        self._step = make_train_step(self.model, self.optimizer,
                                     aux_loss_weight)
        self._init_cache = {}

    def init_state(self, rng: jax.Array, example_tokens: jnp.ndarray) -> TrainState:
        key = (example_tokens.shape, str(example_tokens.dtype))
        if key not in self._init_cache:
            self._init_cache[key] = make_sharded_init(
                self.model, self.optimizer, self.mesh, self.rules,
                example_tokens)
        with ring_context(self.mesh):
            return self._init_cache[key](rng)

    def shard_batch(self, tokens: jnp.ndarray) -> jnp.ndarray:
        return jax.device_put(tokens, batch_sharding(self.mesh, tokens.shape))

    def train_step(self, state: TrainState, tokens: jnp.ndarray):
        # ring_context makes the mesh ambient while jit traces, so
        # attn_impl="ring" models can build their seq-axis shard_map.
        with ring_context(self.mesh):
            return self._step(state, tokens)
