"""Persistent compilation cache + AOT warmup + cost-analysis-exact FLOPs.

Every pod restart / elastic failover used to re-pay the full XLA compile
(minutes at the bench shape) before the first step ran. Three fixes, all
driven from here so the operator and the compute plane agree:

* ``setup_compilation_cache`` — point jax at a persistent on-disk cache
  (``JAX_COMPILATION_CACHE_DIR``, injected into every slice-host pod by the
  TPUJob reconciler as a node-local hostPath mount); compiled programs are
  content-addressed, so all hosts of a slice — and every restart on the same
  node — share warm entries.

* ``aot_compile_train_step`` — ``jit(step).lower(...).compile()`` warmup:
  compilation happens at a chosen point (before the loop starts timing /
  serving), not lazily inside the first step, and the returned executable
  exposes ``cost_analysis()``.

* ``compiled_flops`` / ``train_step_flops`` — the compiler's *exact* FLOP
  count for one step, replacing the 6·N·T estimate as the MFU denominator
  (bench.py logs both: 6·N·T stays for cross-round continuity, but the
  utilization number now reflects what the hardware actually executed,
  including remat recompute and attention FLOPs the parameter-count formula
  misses).

The TPU latency-hiding flag set (``LIBTPU_INIT_ARGS`` async-collective
fusion/overlap) lives in `tpu_on_k8s/api/constants.py` — the reconciler
injects it from there; ``apply_perf_env`` applies the same set for
hand-launched processes, never overriding explicit operator/user values.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Mapping, MutableMapping, Optional, Tuple

from tpu_on_k8s.api import constants

DEFAULT_MIN_COMPILE_SECONDS = 1.0


def setup_compilation_cache(directory: Optional[str] = None,
                            min_compile_seconds: float = DEFAULT_MIN_COMPILE_SECONDS,
                            ) -> Optional[str]:
    """Enable jax's persistent compilation cache at ``directory``.

    Defaults to ``$JAX_COMPILATION_CACHE_DIR`` (the reconciler-injected
    contract); returns the directory in effect, or None when neither the
    argument nor the env names one (a no-op — callers need no guard).
    Idempotent: safe to call before or after backend initialization; only
    compiles *after* the call land in the cache.
    """
    directory = directory or os.environ.get(
        constants.ENV_JAX_COMPILATION_CACHE_DIR)
    if not directory:
        return None
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_seconds))
    return directory


def apply_perf_env(env: Optional[MutableMapping[str, str]] = None,
                   ) -> Mapping[str, str]:
    """Set the TPU latency-hiding flags (``LIBTPU_INIT_ARGS``) in ``env``
    (default ``os.environ``) unless already present — explicit settings from
    the operator or the user always win. Must run before the TPU backend
    initializes to take effect. Returns the mapping for chaining."""
    if env is None:
        env = os.environ
    env.setdefault(constants.ENV_LIBTPU_INIT_ARGS, constants.LIBTPU_PERF_ARGS)
    return env


def aot_compile(jitted: Any, *args: Any, **kwargs: Any) -> Any:
    """``jitted.lower(*args).compile()`` — ahead-of-time compilation of any
    jit-wrapped function. The returned executable is directly callable (with
    the donation/sharding semantics of the original jit) and exposes
    ``cost_analysis()``."""
    return jitted.lower(*args, **kwargs).compile()


def aot_compile_train_step(trainer: Any, state: Any, tokens: Any) -> Any:
    """AOT-compile a ``Trainer``'s jitted step for concrete (state, batch)
    avals. Runs under the trainer's mesh context so ring/flash shard_maps
    trace exactly as they would in ``train_step``."""
    from tpu_on_k8s.parallel.ring import ring_context

    with ring_context(trainer.mesh):
        return aot_compile(trainer._step, state, tokens)


def compiled_flops(compiled: Any) -> Optional[float]:
    """FLOPs of one invocation from the compiler's cost analysis, or None
    when the backend doesn't report one (cost analysis is per-platform; CPU
    and TPU both do, interpreters may not). Under SPMD the count is for the
    PER-DEVICE program — divide by per-chip peak (not aggregate peak) for
    utilization; the shards are symmetric, so that equals global MFU."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — optional introspection, never fatal
        return None
    # jax returns a dict on recent versions, a one-element list of dicts on
    # older ones; normalize.
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        return None
    flops = analysis.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def train_step_flops(trainer: Any, state: Any, tokens: Any,
                     ) -> Tuple[Optional[float], Any]:
    """(exact per-step FLOPs or None, the compiled executable) for a
    Trainer step at concrete avals — the MFU denominator plus a warm
    executable the caller can drive directly (no jit dispatch overhead)."""
    compiled = aot_compile_train_step(trainer, state, tokens)
    return compiled_flops(compiled), compiled


def analytic_train_flops(n_params: int, tokens_per_step: int) -> float:
    """The classic 6·N·T estimate (2N forward + 4N backward per token) —
    kept as the continuity number logged beside the cost-analysis value."""
    return 6.0 * float(n_params) * float(tokens_per_step)


def perf_env() -> Dict[str, str]:
    """The full env contract the reconciler injects into slice-host pods —
    one place to read it from tooling/tests."""
    return {
        constants.ENV_JAX_COMPILATION_CACHE_DIR:
            constants.DEFAULT_COMPILE_CACHE_DIR,
        constants.ENV_LIBTPU_INIT_ARGS: constants.LIBTPU_PERF_ARGS,
    }
