"""Core object model: the slice of corev1/metav1 the framework needs.

The reference imports k8s.io/api/core/v1 wholesale; this framework only touches a
narrow surface (pods, services, env, volumes, resource lists), so that surface is
defined here as plain dataclasses. Everything round-trips through
``tpu_on_k8s.utils.serde`` — no generated code.
"""
from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_on_k8s.utils import serde


def utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: Optional[_dt.datetime] = None
    deletion_timestamp: Optional[_dt.datetime] = None
    generation: int = 0
    resource_version: int = 0

    def controller_ref(self) -> Optional[OwnerReference]:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None


@dataclass
class EnvVarSource:
    """Downward-API field reference. The reference uses
    ``fieldRef: metadata.annotations['distributed.io/world-size']`` so an in-place
    restarted container observes the *new* world size
    (/root/reference/controllers/train/torchjob_controller.go:419-439).

    Wire shape is core/v1's ``valueFrom: {fieldRef: {fieldPath: ...}}``
    nesting (the flat form is internal only)."""

    field_path: str = ""

    @staticmethod
    def __wire_out__(d: Dict[str, object]) -> Dict[str, object]:
        fp = d.pop("fieldPath", None)
        return {"fieldRef": {"fieldPath": fp}} if fp else d

    @staticmethod
    def __wire_in__(d: Dict[str, object]) -> Dict[str, object]:
        fr = d.get("fieldRef")
        if isinstance(fr, dict) and "fieldPath" in fr:
            d = dict(d)
            d["field_path"] = fr["fieldPath"]
        return d


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""
    value_from: Optional[EnvVarSource] = None


@dataclass
class ContainerPort:
    name: str = ""
    container_port: int = 0
    host_port: int = 0


@dataclass
class ResourceRequirements:
    """Resource requests/limits as plain quantity maps.

    Quantities are numeric (chips, cores, bytes) rather than k8s quantity strings —
    the TPU resource key is ``google.com/tpu`` (chips per host).
    """

    requests: Dict[str, float] = field(default_factory=dict)
    limits: Dict[str, float] = field(default_factory=dict)


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False


@dataclass
class Volume:
    """Tagged-union volume source: exactly one of the source fields is set.
    ``items`` maps source keys to file paths under the mount (configmap/secret
    projections, e.g. ``.dockerconfigjson`` → ``config.json``)."""

    name: str = ""
    host_path: Optional[str] = None
    nfs_server: Optional[str] = None
    nfs_path: Optional[str] = None
    pvc_claim_name: Optional[str] = None
    config_map_name: Optional[str] = None
    secret_name: Optional[str] = None
    empty_dir: bool = False
    items: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def __wire_out__(d: Dict[str, object]) -> Dict[str, object]:
        """Emit core/v1's nested volume sources (``hostPath: {path}``,
        ``nfs: {server, path}``, ``persistentVolumeClaim: {claimName}``, …) —
        a real apiserver rejects the internal flat-string form."""
        items = d.pop("items", None) or {}
        wire_items = [{"key": k, "path": p} for k, p in items.items()]
        out: Dict[str, object] = {"name": d.get("name", "")}
        if d.get("hostPath"):
            out["hostPath"] = {"path": d["hostPath"]}
        if d.get("nfsServer"):
            out["nfs"] = {"server": d["nfsServer"],
                          "path": d.get("nfsPath") or ""}
        if d.get("pvcClaimName"):
            out["persistentVolumeClaim"] = {"claimName": d["pvcClaimName"]}
        if d.get("configMapName"):
            cm: Dict[str, object] = {"name": d["configMapName"]}
            if wire_items:
                cm["items"] = wire_items
            out["configMap"] = cm
        if d.get("secretName"):
            sec: Dict[str, object] = {"secretName": d["secretName"]}
            if wire_items:
                sec["items"] = wire_items
            out["secret"] = sec
        if d.get("emptyDir"):
            out["emptyDir"] = {}
        return out

    @staticmethod
    def __wire_in__(d: Dict[str, object]) -> Dict[str, object]:
        sources = ("hostPath", "nfs", "persistentVolumeClaim", "configMap",
                   "secret", "emptyDir")
        if not any(k in d for k in sources):
            return d  # internal snake_case / legacy flat form
        out: Dict[str, object] = {"name": d.get("name", "")}
        hp = d.get("hostPath")
        out["host_path"] = hp.get("path") if isinstance(hp, dict) else hp
        nfs = d.get("nfs")
        if isinstance(nfs, dict):
            out["nfs_server"] = nfs.get("server")
            out["nfs_path"] = nfs.get("path")
        pvc = d.get("persistentVolumeClaim")
        if isinstance(pvc, dict):
            out["pvc_claim_name"] = pvc.get("claimName")
        items = None
        cm = d.get("configMap")
        if isinstance(cm, dict):
            out["config_map_name"] = cm.get("name")
            items = cm.get("items")
        sec = d.get("secret")
        if isinstance(sec, dict):
            out["secret_name"] = sec.get("secretName")
            items = items or sec.get("items")
        ed = d.get("emptyDir")
        out["empty_dir"] = True if isinstance(ed, dict) else bool(ed)
        if isinstance(items, list):
            out["items"] = {e["key"]: e["path"] for e in items}
        elif isinstance(d.get("items"), dict):
            out["items"] = d["items"]
        return out


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    working_dir: str = ""
    termination_message_policy: str = ""

    def env_map(self) -> Dict[str, str]:
        return {e.name: e.value for e in self.env}

    def set_env(self, name: str, value: str = "", value_from: Optional[EnvVarSource] = None) -> None:
        for e in self.env:
            if e.name == name:
                e.value, e.value_from = value, value_from
                return
        self.env.append(EnvVar(name=name, value=value, value_from=value_from))


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    restart_policy: str = "Never"  # pod-level: Never|OnFailure|Always
    node_selector: Dict[str, str] = field(default_factory=dict)
    scheduler_name: str = ""
    priority_class_name: str = ""
    priority: Optional[int] = None
    host_network: bool = False
    hostname: str = ""
    subdomain: str = ""
    node_name: str = ""
    volumes: List[Volume] = field(default_factory=list)

    def container(self, name: str) -> Optional[Container]:
        for c in self.containers:
            if c.name == name:
                return c
        return None

    def default_container(self) -> Optional[Container]:
        """The conventional main container ("tpu"), falling back to the first
        (single shared lookup — the reference re-implemented this scan in three
        places, one with an index bug, hostnetwork.go:54-62)."""
        from tpu_on_k8s.api import constants  # late: constants has no deps

        return self.container(constants.DEFAULT_CONTAINER_NAME) or (
            self.containers[0] if self.containers else None
        )

    def coordinator_port(self) -> int:
        """The declared coordinator port of the default container, or the
        framework default."""
        from tpu_on_k8s.api import constants

        c = self.default_container()
        if c is not None:
            for p in c.ports:
                if p.name == constants.DEFAULT_PORT_NAME:
                    return p.container_port
        return constants.DEFAULT_COORDINATOR_PORT


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    reason: str = ""
    message: str = ""


@dataclass
class ContainerStatus:
    name: str = ""
    ready: bool = False
    restart_count: int = 0
    terminated: Optional[ContainerStateTerminated] = None

    @staticmethod
    def __wire_out__(d: Dict[str, object]) -> Dict[str, object]:
        """core/v1 nests termination under ``state: {terminated: {...}}``;
        the flat ``terminated`` is internal only."""
        t = d.pop("terminated", None)
        if t is not None:
            d["state"] = {"terminated": t}
        return d

    @staticmethod
    def __wire_in__(d: Dict[str, object]) -> Dict[str, object]:
        st = d.get("state")
        if (isinstance(st, dict) and "terminated" not in d
                and st.get("terminated") is not None):
            d = dict(d)
            d["terminated"] = st["terminated"]
        return d


@dataclass
class Condition:
    type: str = ""
    status: str = "True"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[_dt.datetime] = None


class PodPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"

    ORDER = {PENDING: 0, RUNNING: 1, SUCCEEDED: 2, FAILED: 3, UNKNOWN: 4}


@dataclass
class PodStatus:
    phase: str = PodPhase.PENDING
    reason: str = ""
    message: str = ""
    pod_ip: str = ""
    host_ip: str = ""
    start_time: Optional[_dt.datetime] = None
    conditions: List[Condition] = field(default_factory=list)
    container_statuses: List[ContainerStatus] = field(default_factory=list)

    def is_ready(self) -> bool:
        return any(c.type == "Ready" and c.status == "True" for c in self.conditions)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class Pod:
    api_version: str = "v1"
    kind: str = "Pod"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0


@dataclass
class ServiceSpec:
    cluster_ip: str = ""  # "None" => headless
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)


@dataclass
class Service:
    api_version: str = "v1"
    kind: str = "Service"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)


@dataclass
class ResourceQuotaSpec:
    hard: Dict[str, float] = field(default_factory=dict)


@dataclass
class ResourceQuotaStatus:
    used: Dict[str, float] = field(default_factory=dict)


@dataclass
class ResourceQuota:
    """Namespace resource budget — the quota surface the coordinator's quota
    plugin sums (reference plugins/quota.go:97-131)."""

    api_version: str = "v1"
    kind: str = "ResourceQuota"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)


@dataclass
class ConfigMap:
    """Plain key→value config object (the model pipeline's dockerfile carrier,
    reference modelversion_controller.go:286-311)."""

    api_version: str = "v1"
    kind: str = "ConfigMap"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class PriorityClass:
    """Priority class value source for the coordinator's priority plugin
    (reference plugins/priority.go:74-87)."""

    api_version: str = "scheduling.k8s.io/v1"
    kind: str = "PriorityClass"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0


@dataclass
class ObjectReference:
    """core/v1 ObjectReference (the involvedObject of an Event)."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class Event:
    """Real core/v1 Event object (reference record.EventRecorder emits these;
    round 2 stored ad-hoc tuples — a conformant apiserver only accepts this
    shape)."""

    api_version: str = "v1"
    kind: str = "Event"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    type: str = "Normal"          # Normal | Warning
    reason: str = ""
    message: str = ""
    count: int = 1
    first_timestamp: Optional[_dt.datetime] = None
    last_timestamp: Optional[_dt.datetime] = None
    reporting_component: str = "tpu-on-k8s-manager"


def deep_copy(obj):
    return serde.deep_copy(obj)
