"""InferenceService API type: the serving half of the model pipeline.

The reference operator's serving story ends the moment the trained
artifact is baked into an OCI image (SURVEY §3.5 — ModelVersion sets
``Model.status.latest_image`` and stops). An ``InferenceService`` is the
missing kind that *deploys* that image: a declarative request for N
engine replicas on TPU slices, following a ``Model``'s latest image, with
a rollout policy that governs how traffic and capacity move when a new
``ModelVersion`` lands.

Two planes consume this type:

* `controller/inferenceservice.py` reconciles gang-scheduled replica pods
  from the spec (one gang of ``hosts_per_slice`` pods per replica) and
  drives the rolling rollout — surge within ``max_surge``, drain old
  replicas before deletion, never dip below
  ``replicas - max_unavailable`` ready;
* `serve/fleet.py` is the in-process realization of the same state
  machine: a ``ServingFleet`` owning one gateway per replica and a
  ``Router`` that honors the canary weight while a rollout progresses.

``RolloutPolicy`` deliberately mirrors a Deployment's rollingUpdate knobs
(maxSurge / maxUnavailable) plus the serving-specific ``canary_weight`` —
the traffic share the FIRST ready new-version replica receives before the
fleet commits to shifting the rest.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import ObjectMeta
from tpu_on_k8s.api.types import TPUPolicy


@dataclass
class RolloutPolicy:
    """How a new model image replaces the old one under live traffic.

    ``max_surge`` extra replicas may exist above ``spec.replicas`` during
    a rollout (capacity first, then traffic); at most ``max_unavailable``
    of the desired replicas may be not-ready at any instant.
    ``canary_weight`` is the router share granted to the new version once
    its first replica is ready — held until more new replicas come up,
    after which the share grows with the replaced fraction.
    ``drain_seconds`` is the grace an old replica gets between
    stop-accepting and deletion (the serving analog of
    terminationGracePeriodSeconds)."""

    max_surge: int = 1
    max_unavailable: int = 0
    canary_weight: float = 0.1
    drain_seconds: float = 30.0

    def normalized(self) -> "RolloutPolicy":
        """Defaulted-and-clamped copy (API types stay passive records, like
        the reference's defaulting webhook shape): surge/unavailable floors
        at 0, surge forced to >= 1 when both knobs are 0 (a rollout that can
        neither add nor remove a replica would wedge), canary weight clamped
        to [0, 1], drain floored at 0."""
        surge = max(int(self.max_surge), 0)
        unavail = max(int(self.max_unavailable), 0)
        if surge == 0 and unavail == 0:
            surge = 1
        return RolloutPolicy(
            max_surge=surge, max_unavailable=unavail,
            canary_weight=min(max(float(self.canary_weight), 0.0), 1.0),
            drain_seconds=max(float(self.drain_seconds), 0.0))


@dataclass
class DecodePolicy:
    """Decode-path acceleration for a serving replica — the knobs behind
    the tokens-per-chip headline (`tpu_on_k8s/models/serving.py`):

    * ``draft_model`` names the small draft checkpoint (a ``Model``
      ref, e.g. a GPT-2 draft loaded via the HF interop layer in
      `models/convert.py`) for batched speculative decoding; ``""``
      disables speculation. ``spec_k`` is the proposals-per-round
      window.
    * ``int8_weights`` serves W8A16 int8 weights
      (`models/convert.quantize_serving_tree`) instead of bf16 —
      ~half the weight bytes in the bandwidth-bound decode loop.

    A DecodePolicy change is part of what a replica RUNS: the
    reconciler folds it into the replica-group identity hash, so
    flipping int8 (or the draft) rolls the fleet through the SAME
    surge/drain/canary machinery a new image does — the router's canary
    split A/Bs the variant under live traffic before the fleet commits
    (`controller/inferenceservice.py`, `serve/router.py`)."""

    draft_model: str = ""
    spec_k: int = 4
    int8_weights: bool = False

    def normalized(self) -> "DecodePolicy":
        """Defaulted-and-clamped copy (same passive-record shape as
        ``RolloutPolicy``): the speculation window floors at 1."""
        return DecodePolicy(
            draft_model=str(self.draft_model),
            spec_k=max(int(self.spec_k), 1),
            int8_weights=bool(self.int8_weights))


@dataclass
class ShardingPolicy:
    """How each serving replica shards its engine over its slice's
    chips (`tpu_on_k8s/models/serving.py` mesh path over
    `parallel/mesh.serving_mesh`): ``data`` × ``model`` × ``expert``
    must equal the replica's chip count. ``model`` carries
    tensor-parallel decode (attention heads / MLP dims split, per-layer
    collectives on ICI — the axis that lets one replica serve a model
    bigger than one chip's HBM), ``expert`` shards MoE expert tables,
    ``data`` splits the slot pool. ``rules`` names the partition-rule
    preset (``"serving"`` — `transformer.serving_partition_rules`, the
    int8-aware Megatron layout; ``"flagship"`` — the raw training
    rules).

    Like ``DecodePolicy``, the sharding is part of what a replica RUNS:
    the reconciler folds it into the replica identity hash, so changing
    the mesh shape ROLLS the fleet (surge → canary → drain) — params
    cannot be relaid out under a live engine's compiled programs. An
    absent block (or the all-1 default) is the single-program engine,
    bit-for-bit."""

    data: int = 1
    model: int = 1
    expert: int = 1
    rules: str = "serving"

    def normalized(self) -> "ShardingPolicy":
        """Defaulted-and-clamped copy (passive record, like
        ``RolloutPolicy``): axis sizes floor at 1; unknown rule presets
        fall back to "serving"."""
        rules = str(self.rules or "serving")
        if rules not in ("serving", "flagship"):
            rules = "serving"
        return ShardingPolicy(
            data=max(int(self.data), 1), model=max(int(self.model), 1),
            expert=max(int(self.expert), 1), rules=rules)

    @property
    def chips(self) -> int:
        """Chips one replica's mesh spans."""
        n = self.normalized()
        return n.data * n.model * n.expert

    def is_trivial(self) -> bool:
        """All-1 axes = the single-program engine: applying
        ``sharding: {}`` to a running fleet must not trigger a no-op
        rollout (same principle as ``decode: {}``)."""
        n = self.normalized()
        return n.data == n.model == n.expert == 1


@dataclass
class AutoscalePolicy:
    """SLO-driven replica autoscaling for the serving fleet (consumed by
    `controller/fleetautoscaler.py`; decision core in
    `tpu_on_k8s/autoscale/policy.py`). Setting this block opts the
    service into autoscaling: ``spec.replicas`` becomes the
    autoscaler's output rather than a hand-set value.

    ``target_ttft_s`` / ``target_queue_wait_s`` are the latency SLOs
    (p95, seconds; 0 disables that signal). ``util_high``/``util_low``
    bound tokens-in-flight per engine slot — the early-warning band that
    scales up before latency degrades. ``min_warm`` is the warm floor:
    replicas pre-provisioned for burst absorption, because a TPU slice
    spins up in minutes and reactive-only scaling structurally misses
    the front of every burst. ``hysteresis`` is the dead band around
    each target; ``max_step`` bounds how many slice-legal quanta one
    decision may jump; cooldowns and ``flap_guard_s`` (minimum spacing
    between direction reversals) set the tempo. ``slice_legal`` snaps
    targets to `gang/topology` host-count quanta for the service's
    accelerator (on a 3D-torus part, N+1 replicas may simply not
    exist)."""

    min_replicas: int = 1
    max_replicas: int = 8
    min_warm: int = 0
    target_ttft_s: float = 0.0
    target_queue_wait_s: float = 0.0
    #: TPOT p95 SLO (seconds per output token; 0 disables): the decode
    #: pool's scaling signal in disaggregated serving — queue-wait says
    #: "prefill cannot keep up", TPOT says "decode cannot keep up"
    target_tpot_s: float = 0.0
    #: model swap-in latency p95 SLO (seconds; 0 disables) for
    #: multi-model replicas (`serve/modelpool.py`): swap-in is the
    #: pool's cold-start cost, and when its p95 breaches this target the
    #: density bet has failed — models are fighting over too few
    #: replicas and the fleet needs more residency, exactly like a TTFT
    #: breach says it needs more decode seats
    target_swap_s: float = 0.0
    util_high: float = 0.0
    util_low: float = 0.0
    hysteresis: float = 0.1
    max_step: int = 1
    scale_up_cooldown_s: float = 30.0
    scale_down_cooldown_s: float = 120.0
    flap_guard_s: float = 180.0
    slice_legal: bool = True

    def normalized(self) -> "AutoscalePolicy":
        """Defaulted-and-clamped copy (same passive-record defaulting
        shape as ``RolloutPolicy``): floors at 1 replica, max >= min,
        warm floor within [0, max], non-negative targets/tempo, at
        least one legal step per decision."""
        lo = max(int(self.min_replicas), 1)
        hi = max(int(self.max_replicas), lo)
        return AutoscalePolicy(
            min_replicas=lo, max_replicas=hi,
            min_warm=min(max(int(self.min_warm), 0), hi),
            target_ttft_s=max(float(self.target_ttft_s), 0.0),
            target_queue_wait_s=max(float(self.target_queue_wait_s), 0.0),
            target_tpot_s=max(float(self.target_tpot_s), 0.0),
            target_swap_s=max(float(self.target_swap_s), 0.0),
            util_high=max(float(self.util_high), 0.0),
            util_low=max(float(self.util_low), 0.0),
            hysteresis=max(float(self.hysteresis), 0.0),
            max_step=max(int(self.max_step), 1),
            scale_up_cooldown_s=max(float(self.scale_up_cooldown_s), 0.0),
            scale_down_cooldown_s=max(float(self.scale_down_cooldown_s),
                                      0.0),
            flap_guard_s=max(float(self.flap_guard_s), 0.0),
            slice_legal=bool(self.slice_legal))


#: latency-percentile objectives an ``SLOObjective`` may target (the
#: ``availability`` objective rides beside them); the catalog matches
#: `tpu_on_k8s/obs/slo.py` — the engine that evaluates these specs
SLO_OBJECTIVES = ("ttft_p95", "tpot_p95", "queue_wait_p95",
                  "availability")


@dataclass
class SLOObjective:
    """One declarative service-level objective: *what* is measured
    (``objective``), the ``target`` (seconds for latency percentiles; a
    fraction like 0.999 for availability), and the compliance
    ``window_s`` the error budget covers. The four burn windows default
    to the SRE ratios of ``window_s`` (5m/1h fast pair pages at
    ``page_burn``; 6h/3d slow pair warns at ``warn_burn`` — at the
    30-day default) and may be pinned explicitly. ``name`` keys the
    objective in ``status.slo`` and the metric labels."""

    name: str = ""
    objective: str = "ttft_p95"
    target: float = 0.0
    window_s: float = 2_592_000.0          # 30 days
    fast_short_s: float = 0.0              # 0 → window_s/8640
    fast_long_s: float = 0.0               # 0 → window_s/720
    slow_short_s: float = 0.0              # 0 → window_s/120
    slow_long_s: float = 0.0               # 0 → window_s/10
    page_burn: float = 14.4
    warn_burn: float = 1.0
    hysteresis: float = 0.2

    def normalized(self) -> Optional["SLOObjective"]:
        """Defaulted-and-clamped copy, or None when the objective can
        never evaluate (unknown objective name, non-positive target) —
        the API layer drops dead objectives rather than raising, the
        same passive-record posture as the other policies (the engine
        itself raises; a CRD must tolerate junk)."""
        if self.objective not in SLO_OBJECTIVES:
            return None
        if float(self.target) <= 0 or float(self.window_s) <= 0:
            return None
        return SLOObjective(
            name=str(self.name) or str(self.objective),
            objective=str(self.objective),
            target=float(self.target),
            window_s=float(self.window_s),
            fast_short_s=max(float(self.fast_short_s), 0.0),
            fast_long_s=max(float(self.fast_long_s), 0.0),
            slow_short_s=max(float(self.slow_short_s), 0.0),
            slow_long_s=max(float(self.slow_long_s), 0.0),
            page_burn=max(float(self.page_burn), 1.0),
            warn_burn=max(float(self.warn_burn), 0.0),
            hysteresis=min(max(float(self.hysteresis), 0.0), 0.9))


@dataclass
class SLOPolicy:
    """Service-level objectives for a serving fleet, evaluated by the
    fleet autoscaler's tick (`controller/fleetautoscaler.py` →
    `tpu_on_k8s/obs/slo.py`): every tick feeds the scraped latency
    signals into sliding windows, computes multi-window error-budget
    burn rates per objective, writes the result to ``status.slo``, and
    — when an objective reaches ``page`` — lets one scale-up bypass the
    up-cooldown (dead-banded by the budget-state hysteresis, so a burn
    oscillating at the threshold cannot pump the fleet). Absent, none
    of this runs and the autoscaler's decision logs are byte-identical
    to the pre-SLO behavior."""

    objectives: List[SLOObjective] = field(default_factory=list)

    def normalized(self) -> "SLOPolicy":
        """Drops dead objectives and de-duplicates names (first wins —
        a duplicate would make ``status.slo`` ambiguous)."""
        out: List[SLOObjective] = []
        seen = set()
        for obj in self.objectives:
            norm = obj.normalized()
            if norm is None or norm.name in seen:
                continue
            seen.add(norm.name)
            out.append(norm)
        return SLOPolicy(objectives=out)


@dataclass
class SLOObjectiveStatus:
    """One objective's evaluated budget state in ``status.slo``:
    ``state`` is ``ok``/``warn``/``page``/``exhausted``; burn rates are
    the multi-window pair burns (-1 = no data in the window — JSON has
    no NaN, and absent-vs-zero must stay distinguishable on the wire);
    ``budget_remaining`` is the fraction of the window's error budget
    left (negative = overdrawn). ``stale`` means the signal source went
    dark — the burn rates are unknowable, NOT whatever they last were."""

    objective: str = ""
    target: float = 0.0
    state: str = "ok"
    burn_fast: float = -1.0
    burn_slow: float = -1.0
    budget_remaining: float = 1.0
    stale: bool = False


@dataclass
class ModelRef:
    """One model of a multi-model service (``spec.models``): a replica's
    ``ModelPool`` (`serve/modelpool.py`) hosts ALL of them behind one
    engine and hot-swaps the active params. ``name`` keys everything —
    the pool's request lanes, ledger ``model_swap`` records, metric
    labels, and ``status.models``. ``model_name`` follows that
    ``Model``'s ``status.latest_image`` (the same closed loop as
    ``spec.model_name``); ``image`` pins an explicit image and wins.
    All pooled models MUST share the service's config shape — a
    params-tree replace cannot change architecture (the pool's swap path
    enforces it; a mismatched ref surfaces as a swap failure, never a
    silent misload).

    ``token_budget`` is a per-model tokens/sec admission budget riding
    the tenant accounting plane (`serve/admission.py` — the model id is
    the tenant key; 0 = unlimited). ``slo`` carries per-MODEL objectives
    the fleet autoscaler evaluates into ``status.models[name].slo``
    beside the service-level ``spec.slo``."""

    name: str = ""
    model_name: str = ""
    image: str = ""
    token_budget: int = 0
    slo: Optional[SLOPolicy] = None

    def normalized(self) -> Optional["ModelRef"]:
        """Defaulted-and-clamped copy, or None for an unkeyable ref
        (empty ``name``) — the same drop-dead-entries posture as
        ``SLOObjective``."""
        if not str(self.name):
            return None
        return ModelRef(
            name=str(self.name),
            model_name=str(self.model_name),
            image=str(self.image),
            token_budget=max(int(self.token_budget), 0),
            slo=self.slo.normalized() if self.slo is not None else None)


@dataclass
class ModelStatus:
    """One pooled model's observed state in ``status.models``: the
    ``image`` the reconciler resolved for it (model-ref indirection
    follows ``Model.status.latest_image`` — pool membership converges by
    WEIGHT HOT-SWAP from here, never a pod rollout), a coarse ``phase``
    (``Pending`` while no image exists to load), and the per-model
    ``slo`` budget states the fleet autoscaler's tick writes (same shape
    as the service-level ``status.slo``)."""

    name: str = ""
    image: str = ""
    phase: str = "Pending"
    slo: Dict[str, SLOObjectiveStatus] = field(default_factory=dict)


@dataclass
class PoolSpec:
    """One pool of a disaggregated service (`tpu_on_k8s/serve/disagg.py`).
    ``replicas`` is that pool's size — hand-set, or owned by the fleet
    autoscaler when ``autoscale`` is present (the per-pool twin of
    ``spec.autoscale``: queue-wait p95 is the natural target for the
    prefill pool, TPOT p95 for the decode pool)."""

    replicas: int = 1
    autoscale: Optional[AutoscalePolicy] = None

    def normalized(self) -> "PoolSpec":
        return PoolSpec(
            replicas=max(int(self.replicas), 1),
            autoscale=(self.autoscale.normalized()
                       if self.autoscale is not None else None))


@dataclass
class PoolsSpec:
    """Opt-in disaggregated prefill/decode serving: present, the service
    splits into a prefill pool (chunked prefill only, KV handoff out)
    and a decode pool (admits only handed-off KV), separately sized and
    separately autoscaled. Absent, the service runs today's monolithic
    replicas bit-for-bit. Engine shaping (slot counts, the handoff
    queue bound) stays with the runtime that builds the ``DisaggFleet``
    — a spec field the reconciler cannot yet honor (it does not mint
    pool-labelled pods) would silently do nothing."""

    prefill: PoolSpec = field(default_factory=PoolSpec)
    decode: PoolSpec = field(default_factory=PoolSpec)

    def normalized(self) -> "PoolsSpec":
        return PoolsSpec(
            prefill=self.prefill.normalized(),
            decode=self.decode.normalized())


@dataclass
class BrokerPolicy:
    """How the service participates in the capacity market
    (`tpu_on_k8s/coordinator/broker.py`). ``priority`` orders the
    broker's victim search — a lane only ever loses chips to a
    STRICTLY higher-priority lane under pressure. ``unit_chips`` is
    the chips one replica occupies (the bid's allocation-unit size);
    ``preemption_cost`` is the tie-breaker among equal-priority
    victims (cheapest eviction first). ``degrade`` gates the rung-1
    pressure valve: allowed, the broker may flip this service onto
    cheaper ``DecodePolicy`` variants (int8 weights, deeper
    speculation) before taking anyone's chips. Absent ⇒ serving
    defaults (top priority, 1 chip per replica, degradable).

    ``priced`` opts the service into OBSERVED-signal bid pricing: the
    fleet autoscaler derives the bid's ``marginal_utility`` from the
    live SLO fast-burn rate plus queue depth per slot instead of the
    static 0.0 every unpriced bid carries — a burning, backed-up
    service becomes strictly more expensive to pick as a victim among
    equal-priority bids. Default off: all-static configs produce
    byte-identical broker decisions to pre-``priced`` builds."""

    priority: int = 100
    unit_chips: int = 1
    preemption_cost: float = 1.0
    degrade: bool = True
    priced: bool = False

    def normalized(self) -> "BrokerPolicy":
        return BrokerPolicy(
            priority=int(self.priority),
            unit_chips=max(int(self.unit_chips), 1),
            preemption_cost=max(float(self.preemption_cost), 0.0),
            degrade=bool(self.degrade),
            priced=bool(self.priced))


@dataclass
class InferenceServiceSpec:
    """``model_name`` follows that Model's ``status.latest_image`` (the
    closed train → image → deploy loop); ``image`` pins an explicit image
    instead (and wins when both are set). ``tpu_policy`` is the slice
    each replica occupies — a replica is one gang of ``hosts_per_slice``
    pods. ``n_slots`` / ``prefix_bucket_len`` parameterize the engine and
    router inside each replica (the serve plane reads them; the
    controller passes them through as env)."""

    model_name: str = ""
    image: str = ""
    replicas: int = 1
    tpu_policy: TPUPolicy = field(default_factory=TPUPolicy)
    rollout: RolloutPolicy = field(default_factory=RolloutPolicy)
    n_slots: int = 8
    prefix_bucket_len: int = 128
    #: present = autoscaled: `controller/fleetautoscaler.py` owns
    #: ``replicas`` (within [min_replicas, max_replicas]) from here on
    autoscale: Optional[AutoscalePolicy] = None
    #: present = disaggregated: replicas split into prefill/decode pools
    #: with KV handoff between them (`serve/disagg.py`); each pool's
    #: ``replicas`` is sized by its own ``PoolSpec`` (and, when that
    #: pool carries an ``autoscale`` block, by the fleet autoscaler's
    #: per-pool loop). Absent ⇒ monolithic serving, unchanged.
    pools: Optional[PoolsSpec] = None
    #: present = decode acceleration (speculative drafts and/or int8
    #: serving weights). Part of the replica-group identity: changing it
    #: rolls the fleet (surge/drain/canary) like a new image would.
    decode: Optional[DecodePolicy] = None
    #: present = mesh-sharded replicas: each engine runs
    #: tensor/expert-parallel over a {data, model, expert} mesh of its
    #: slice's chips. Part of the replica-group identity like
    #: ``decode``: a resharding ROLLS the fleet through the same
    #: surge/canary/drain machinery — never a live relayout.
    sharding: Optional[ShardingPolicy] = None
    #: present = SLO evaluation: the fleet autoscaler's tick runs the
    #: error-budget burn-rate engine (`tpu_on_k8s/obs/slo.py`) over the
    #: scraped signals, writes ``status.slo``, and treats a paging
    #: objective as a scale-up severity hint. Absent ⇒ behavior-neutral.
    slo: Optional[SLOPolicy] = None
    #: present = explicit capacity-market terms for the broker
    #: (`coordinator/broker.py`); absent ⇒ serving defaults. Only
    #: consulted when the operator runs a broker at all — with none,
    #: this block is inert.
    broker: Optional[BrokerPolicy] = None
    #: non-empty = multi-model density: every replica hosts a
    #: ``ModelPool`` over these refs (`serve/modelpool.py`) and the
    #: router multiplexes by model (`serve/router.route_model`).
    #: MEMBERSHIP edits converge by weight hot-swap through
    #: ``status.models`` — they never roll the fleet; only toggling the
    #: block on/off does (the replica runtime must be built
    #: pool-capable, which is part of the replica identity).
    models: List[ModelRef] = field(default_factory=list)

    def models_normalized(self) -> List[ModelRef]:
        """The live model refs: dead entries dropped, duplicate names
        de-duplicated (first wins — a duplicate would make the pool's
        lanes and ``status.models`` ambiguous)."""
        out: List[ModelRef] = []
        seen = set()
        for ref in self.models:
            norm = ref.normalized()
            if norm is None or norm.name in seen:
                continue
            seen.add(norm.name)
            out.append(norm)
        return out


class ServicePhase(str, enum.Enum):
    PENDING = "Pending"            # no image to deploy yet
    PROGRESSING = "Progressing"    # scaling or rolling a new image
    READY = "Ready"                # all desired replicas on current image
    DEGRADED = "Degraded"          # ready count below the rollout floor


@dataclass
class InferenceServiceStatus:
    """``current_image`` is what the fleet is converging FROM,
    ``target_image`` what it is converging TO (equal once a rollout
    completes). ``canary_weight`` is the router share currently granted
    to ``target_image`` — 0 before the first new replica is ready, 1.0
    at completion — the single number the serve plane needs to split
    traffic consistently with the controller's rollout position."""

    phase: Optional[ServicePhase] = None
    message: str = ""
    current_image: str = ""
    target_image: str = ""
    replicas: int = 0              # replica gangs that exist (any version)
    ready_replicas: int = 0        # replica gangs fully Running+Ready
    updated_replicas: int = 0      # replica gangs on target_image
    canary_weight: float = 0.0
    observed_model_version: str = ""
    # --- autoscaler-owned (written by controller/fleetautoscaler.py) ---
    desired_replicas: int = 0      # the autoscaler's last committed target
    autoscale_message: str = ""    # last decision, human-readable
    #: per-pool committed targets for disaggregated services
    #: (``spec.pools.<pool>.autoscale`` loops) — {"prefill": n, ...}
    pool_desired_replicas: Dict[str, int] = field(default_factory=dict)
    #: per-objective error-budget state (``spec.slo`` present), written
    #: by the fleet autoscaler's tick — objective name → burn rates,
    #: budget remaining, typed state, staleness
    slo: Dict[str, SLOObjectiveStatus] = field(default_factory=dict)
    #: per-model observed state (``spec.models`` non-empty): the
    #: reconciler writes each entry's resolved ``image``/``phase`` (pool
    #: membership converges by hot-swap from here), the fleet autoscaler
    #: writes each entry's ``slo`` budget states
    models: Dict[str, ModelStatus] = field(default_factory=dict)


@dataclass
class InferenceService:
    api_version: str = f"{constants.API_GROUP}/{constants.API_VERSION}"
    kind: str = constants.KIND_INFERENCESERVICE
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: InferenceServiceSpec = field(default_factory=InferenceServiceSpec)
    status: InferenceServiceStatus = field(
        default_factory=InferenceServiceStatus)
