"""TPUJob API types.

TPU-native analog of /root/reference/apis/train/v1alpha1/torchjob_types.go: a job is
a map of task-type → TaskSpec plus a RunPolicy, an ElasticPolicy and (new here) a
TPUPolicy that pins the job to a TPU slice shape. The crucial semantic shift from
the reference (SURVEY §7 "hard parts"): a *task* is a **host in a TPU slice**, so
replica counts are only legal in slice-topology quanta — free-form NumTasks
doubling (reference torchelastic job.go:102-104) is not allowed here; see
``tpu_on_k8s.gang.topology``.
"""
from __future__ import annotations

import datetime as _dt
import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import ObjectMeta, PodTemplateSpec
from tpu_on_k8s.api.model_types import ModelVersionSpec


class TaskType(str, enum.Enum):
    """Reference torchjob_types.go:34-42. AIMaster is an optional user-supplied
    controller task that coordinates checkpoints for elastic scaling."""

    AIMASTER = "AIMaster"
    MASTER = "Master"
    WORKER = "Worker"

    @classmethod
    def normalize(cls, raw: str) -> "TaskType":
        """Case-insensitive task-type normalization (reference defaulting step 1,
        torchjob_defaults.go:33-45)."""
        for t in cls:
            if t.value.lower() == raw.lower():
                return t
        raise ValueError(f"unknown task type {raw!r}")


class RestartPolicy(str, enum.Enum):
    """Reference torchjob_types.go:64-74. ON_EXIT_CODE defers restart decisions to
    the exit-code classifier in ``tpu_on_k8s.controller.failover``."""

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    ON_EXIT_CODE = "OnExitCode"


class CleanPodPolicy(str, enum.Enum):
    RUNNING = "Running"  # delete only still-running pods at job end
    ALL = "All"
    NONE = "None"


class JobConditionType(str, enum.Enum):
    """Job lifecycle FSM states (reference torchjob_types.go:226-239 + utils)."""

    CREATED = "Created"
    QUEUING = "Queuing"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class DAGCondition:
    """Gate creating a task type until an upstream type reaches a phase
    (reference torchjob_types.go:79-84; evaluated by controller.dag)."""

    upstream: TaskType = TaskType.MASTER
    on_phase: str = "Running"


@dataclass
class SpotTaskSpec:
    """Subset of a task's replicas to run at spot priority
    (reference torchjob_types.go SpotTaskSpec; applied in pod creation)."""

    num_spot_tasks: int = 0
    priority_class_name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class TaskSpec:
    """One task type's replica group (reference torchjob_types.go:88-104)."""

    num_tasks: int = 1
    restart_policy: Optional[RestartPolicy] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    spot_task_spec: Optional[SpotTaskSpec] = None
    dag_conditions: List[DAGCondition] = field(default_factory=list)


@dataclass
class SchedulingPolicy:
    """Gang/queue knobs (reference torchjob_types.go:120-135)."""

    min_available: Optional[int] = None
    queue: str = ""
    priority: Optional[int] = None
    priority_class_name: str = ""
    min_members: Dict[TaskType, int] = field(default_factory=dict)


@dataclass
class RunPolicy:
    """Lifecycle policy (reference torchjob_types.go:139-154)."""

    clean_pod_policy: CleanPodPolicy = CleanPodPolicy.RUNNING
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None


@dataclass
class ElasticPolicy:
    """Elastic-training policy (reference TorchElasticPolicy,
    torchjob_types.go:160-173). On TPU, min/max replicas are expressed in *hosts*
    and must land on slice-legal quanta; rendezvous rides the XLA coordinator
    (``xla://``) rather than etcd, but an explicit backend/endpoint may be given."""

    min_replicas: int = 1
    max_replicas: int = 1
    rendezvous_backend: str = "xla"
    rendezvous_endpoint: str = ""
    nproc_per_node: int = 1
    max_restarts: Optional[int] = None
    # live mesh reconfiguration (tpu_on_k8s/parallel/reshard.py): rescale
    # decisions are delivered to the pods as (hosts, mesh shape) reshard
    # requests — training state transforms in place and the run never
    # exits; a failed transform falls back to the checkpoint-restart path
    live_reshard: bool = False


@dataclass
class TPUPolicy:
    """TPU slice binding — the new, TPU-first part of the spec. Drives
    ``google.com/tpu`` resource requests, GKE nodeSelectors, and gang MinMember
    (= slice host count), per BASELINE.json north star."""

    accelerator: str = "tpu-v5-lite-podslice"  # GKE gke-tpu-accelerator value
    topology: str = "2x4"                      # GKE gke-tpu-topology value
    num_slices: int = 1                        # >1 => multi-slice over DCN (Megascale)


@dataclass
class JobCondition:
    type: JobConditionType = JobConditionType.CREATED
    status: str = "True"
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[_dt.datetime] = None
    last_update_time: Optional[_dt.datetime] = None


@dataclass
class ReplicaStatus:
    """Per-task-type counts (reference TaskStatus)."""

    active: int = 0
    ready: int = 0
    succeeded: int = 0
    failed: int = 0
    evicted: int = 0


@dataclass
class ElasticStatus:
    """Per-task-type elastic observation record (reference TorchElasticStatus,
    torchjob_types.go:276-289)."""

    replicas: int = 0
    last_replicas: int = 0
    continue_scaling: bool = False
    message: str = ""
    current_latency: float = 0.0
    last_latency: float = 0.0
    start_time: Optional[_dt.datetime] = None
    last_update_time: Optional[_dt.datetime] = None


@dataclass
class JobStatus:
    """Reference torchjob_types.go:295-310."""

    conditions: List[JobCondition] = field(default_factory=list)
    task_statuses: Dict[TaskType, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[_dt.datetime] = None
    completion_time: Optional[_dt.datetime] = None
    elastic_statuses: Dict[TaskType, ElasticStatus] = field(default_factory=dict)
    model_version_name: str = ""


@dataclass
class TPUJobSpec:
    tasks: Dict[TaskType, TaskSpec] = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    elastic_policy: Optional[ElasticPolicy] = None
    tpu_policy: TPUPolicy = field(default_factory=TPUPolicy)
    # ModelVersion template: when set, task pods get the model volume + path env
    # and a ModelVersion is emitted on success (reference TorchJobSpec's embedded
    # model output spec; controllers/common/job.go:465-508,557-581).
    model_version: Optional[ModelVersionSpec] = None


@dataclass
class TPUJob:
    api_version: str = f"{constants.API_GROUP}/{constants.API_VERSION}"
    kind: str = constants.KIND_TPUJOB
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: JobStatus = field(default_factory=JobStatus)


def extract_meta_fields(job: TPUJob):
    """(tasks, status, scheduling_policy) for the generic engine/coordinator
    (reference apis/train/v1alpha1/common.go:45-55)."""
    return job.spec.tasks, job.status, job.spec.run_policy.scheduling_policy
