"""Well-known labels, annotations, env names and defaults for TPUJob.

TPU-native rework of /root/reference/apis/train/v1alpha1/constants.go and
/root/reference/apis/model/v1alpha1/constants.go. The reference wires NCCL/gloo
rendezvous env (MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE); here the equivalent block
is PJRT/XLA process wiring consumed by jax.distributed / torch_xla.
"""

API_GROUP = "distributed.tpu.io"
API_VERSION = "v1alpha1"

KIND_TPUJOB = "TPUJob"
KIND_MODEL = "Model"
KIND_MODELVERSION = "ModelVersion"
KIND_INFERENCESERVICE = "InferenceService"

# ---- labels (selector surface) ------------------------------------------------
LABEL_JOB_NAME = "tpujob.distributed.tpu.io/job-name"
LABEL_GROUP_NAME = "group-name"
LABEL_TASK_INDEX = "task-index"
LABEL_TASK_TYPE = "task-type"
LABEL_TASK_ROLE = "task-role"
LABEL_JOB_GENERATION = "distributed.tpu.io/job-generation"
LABEL_SPOT_TASK = "distributed.tpu.io/spot-task"
LABEL_MODEL_NAME = "model.distributed.tpu.io/model-name"
# serving fleet (controller/inferenceservice.py): pods of one InferenceService,
# grouped by the image generation they run (label values forbid '/' and ':',
# so the image rides an annotation and a short content hash rides the label)
LABEL_INFERENCESERVICE_NAME = "serving.distributed.tpu.io/inference-service-name"
LABEL_SERVING_IMAGE_HASH = "serving.distributed.tpu.io/image-hash"
LABEL_SERVING_REPLICA_INDEX = "serving.distributed.tpu.io/replica-index"

# ---- annotations (protocol surface) -------------------------------------------
ANNOTATION_NETWORK_MODE = "distributed.tpu.io/network-mode"
NETWORK_MODE_HOST = "host"
ANNOTATION_ENABLE_ELASTIC = "distributed.tpu.io/enable-elastic-training"
ANNOTATION_SCALE_STATE = "distributed.tpu.io/scale-state"
SCALE_STATE_INFLIGHT = "inflight"
SCALE_STATE_DONE = "done"
# 2-phase checkpoint transaction (operator <-> AIMaster), SURVEY §3.3 / §5.4:
ANNOTATION_CKPT_REQUESTED_VERSION = "distributed.tpu.io/ckpt-requested-version"
ANNOTATION_CKPT_COMPLETED_VERSION = "distributed.tpu.io/ckpt-completed-version"
# live mesh reconfiguration (tpu_on_k8s/parallel/reshard.py): the elastic
# autoscaler's (hosts, mesh shape) decision delivered to the pod as a
# reshard REQUEST ("gen=G;hosts=H;mesh=data=2,fsdp=8") instead of a
# delete; the in-pod ReshardAgent transforms training state live and
# acks with the generation, which lets the elastic controller adopt the
# running pods at the new generation without restarting them.
ANNOTATION_RESHARD_REQUESTED_SPEC = "distributed.tpu.io/reshard-requested-spec"
ANNOTATION_RESHARD_COMPLETED_SPEC = "distributed.tpu.io/reshard-completed-spec"
ANNOTATION_READY_TO_START_WORKER = "distributed.tpu.io/ready-to-start-worker"
ANNOTATION_IMMEDIATELY_START_WORKER = "distributed.tpu.io/immediately-start-worker"
ANNOTATION_WORLD_SIZE = "distributed.tpu.io/world-size"
ANNOTATION_LAST_FAILOVER_TIMESTAMP = "distributed.tpu.io/last-failover-timestamp"
# Count of healthy in-place restarts performed by elastic scaling on this pod —
# subtracted from container restart counts so successful rescales never feed
# the job's failure backoff limit.
ANNOTATION_ELASTIC_RESTARTS = "distributed.tpu.io/elastic-restarts"
# The failed-pod incarnation (uid) a surviving slice sibling was last
# restarted for — makes slice-atomic failover idempotent across the
# level-triggered reconcile passes that drive a pending CRR protocol.
ANNOTATION_SLICE_RESTART_FOR = "distributed.tpu.io/slice-restart-for"
# The job generation a pod's cluster spec (world size, hostnames, Megascale
# env) was last refreshed for during elastic rescale. The pod's generation
# LABEL only advances once its in-place restart completes, so staleness
# keeps re-driving a pending restart; this annotation stops the respec
# write itself from repeating on every pass in between.
ANNOTATION_RESPEC_GENERATION = "distributed.tpu.io/respec-generation"
# serving rollout drain protocol (controller/inferenceservice.py): an
# old-version replica pod is marked draining (the serve plane's
# stop_accepting) with an absolute controller-clock deadline; the pod is
# only deleted once the deadline passes, so in-flight requests finish
ANNOTATION_SERVING_DRAIN_DEADLINE = "serving.distributed.tpu.io/drain-deadline"
ANNOTATION_SERVING_IMAGE = "serving.distributed.tpu.io/image"
# gang scheduler podgroup binding (reference: scheduling.k8s.io/group-name,
# /root/reference/pkg/gangscheduler/volcano/volcano.go:238-287)
ANNOTATION_GANG_GROUP_NAME = "scheduling.k8s.io/group-name"

# ---- finalizers ----------------------------------------------------------------
FINALIZER_PREEMPT_PROTECTOR = "distributed.tpu.io/preempt-protector"

# ---- defaults ------------------------------------------------------------------
DEFAULT_CONTAINER_NAME = "tpu"
DEFAULT_PORT_NAME = "tpujob-port"
# XLA distributed coordinator (jax.distributed / torch_xla xla://) default port.
DEFAULT_COORDINATOR_PORT = 8476

# ---- PJRT/XLA env wiring (the MASTER_ADDR/RANK/WORLD_SIZE analog) --------------
ENV_PJRT_DEVICE = "PJRT_DEVICE"                    # "TPU"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"                # task index within the slice
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"  # comma-joined worker DNS names
ENV_COORDINATOR_ADDRESS = "XLA_COORDINATOR_ADDRESS"  # host:port of master-0
ENV_NUM_PROCESSES = "TPU_NUM_PROCESSES"            # WORLD_SIZE analog (hosts)
ENV_PROCESS_ID = "TPU_PROCESS_ID"                  # RANK analog
ENV_MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"  # multi-slice DCN
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_PYTHONUNBUFFERED = "PYTHONUNBUFFERED"

# torchelastic-analog rendezvous CLI args (prepended to user args when elastic):
ARG_RDZV_BACKEND = "--rdzv_backend"
ARG_RDZV_ENDPOINT = "--rdzv_endpoint"
ARG_RDZV_ID = "--rdzv_id"
ARG_NPROC_PER_NODE = "--nproc_per_node"
ARG_NNODES = "--nnodes"

# ---- compile-cache / perf env (single source of truth) -------------------------
# The reconciler injects these into every slice-host pod and the compute plane
# (`tpu_on_k8s/train/compile.py`) consumes them, so the operator and the user
# container can never disagree about where the persistent XLA compilation
# cache lives or which latency-hiding flags are on.
ENV_JAX_COMPILATION_CACHE_DIR = "JAX_COMPILATION_CACHE_DIR"
ENV_LIBTPU_INIT_ARGS = "LIBTPU_INIT_ARGS"
# hostPath mount shared by every pod incarnation on the node: a restarted /
# failed-over worker finds the previous incarnation's compiled programs and
# skips straight to execution (compilation-cache keys are content-addressed,
# so stale entries are never wrong — only unused).
COMPILE_CACHE_VOLUME = "xla-compile-cache"
DEFAULT_COMPILE_CACHE_DIR = "/var/cache/tpu-on-k8s/xla"
# Async-collective latency hiding: fuse collectives with compute and overlap
# them on the TensorCore so ICI hops hide behind matmuls (the standard
# MaxText/scaling-book production set for v4/v5e/v5p).
LIBTPU_PERF_ARGS = (
    "--xla_tpu_enable_async_collective_fusion=true "
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true "
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true "
    "--xla_tpu_overlap_compute_collective_tc=true "
    "--xla_enable_async_all_gather=true"
)
# Profiling hooks (`tpu_on_k8s/utils/profiling.py`, consumed by
# `train/loop.py`): the operator's ``--profile-dir``/``--profiler-port``
# flags land in slice pods as these env vars, so XLA trace capture and the
# live profiler server need no per-trainer plumbing. Unset (the default)
# keeps both hooks dormant.
ENV_PROFILE_DIR = "TPU_ON_K8S_PROFILE_DIR"
ENV_PROFILER_PORT = "TPU_ON_K8S_PROFILER_PORT"

# ---- GKE TPU scheduling surface ------------------------------------------------
RESOURCE_TPU = "google.com/tpu"                     # chips per host
NODE_SELECTOR_TPU_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
NODE_SELECTOR_TPU_TOPOLOGY = "cloud.google.com/gke-tpu-topology"

# ---- model pipeline ------------------------------------------------------------
ENV_MODEL_PATH = "TPU_ON_K8S_MODEL_PATH"
DEFAULT_MODEL_PATH = "/tpu-on-k8s-model"
LABEL_FAST_STORAGE_NODE = "distributed.tpu.io/fast-model-storage"
REGISTRY_SECRET_NAME = "regcred"

# ---- context keys (hostnetwork port map handed through reconcile context) ------
CONTEXT_HOSTNETWORK_PORTS = "hostnetwork-ports"
CONTEXT_GANG_SCHEDULER = "gang-scheduler"
