"""API layer (L1): TPUJob / Model / ModelVersion types, constants and defaulting.

Mirrors the capability surface of the reference's ``apis/`` tree
(/root/reference/apis/train/v1alpha1/torchjob_types.go,
/root/reference/apis/model/v1alpha1/) with a TPU-native spec shape.
"""

from tpu_on_k8s.api.core import (
    Condition,
    Container,
    ContainerPort,
    ContainerStateTerminated,
    ContainerStatus,
    EnvVar,
    ObjectMeta,
    OwnerReference,
    PodSpec,
    PodStatus,
    PodTemplateSpec,
    ResourceRequirements,
    Volume,
    VolumeMount,
)
from tpu_on_k8s.api.types import (
    ElasticPolicy,
    JobCondition,
    JobConditionType,
    JobStatus,
    ReplicaStatus,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    SpotTaskSpec,
    TaskSpec,
    TaskType,
    TPUJob,
    TPUJobSpec,
    TPUPolicy,
    ElasticStatus,
)
from tpu_on_k8s.api.model_types import (
    Model,
    ModelSpec,
    ModelStatus,
    ModelVersion,
    ModelVersionSpec,
    ModelVersionStatus,
    Storage,
    LocalStorage,
    NFSStorage,
    GCSStorage,
)
from tpu_on_k8s.api.defaults import set_defaults_tpujob
from tpu_on_k8s.api import constants

__all__ = [
    "Condition", "Container", "ContainerPort", "ContainerStateTerminated",
    "ContainerStatus", "EnvVar", "ObjectMeta", "OwnerReference", "PodSpec",
    "PodStatus", "PodTemplateSpec", "ResourceRequirements", "Volume", "VolumeMount",
    "ElasticPolicy", "ElasticStatus", "JobCondition", "JobConditionType", "JobStatus",
    "ReplicaStatus", "RestartPolicy", "RunPolicy", "SchedulingPolicy", "SpotTaskSpec",
    "TaskSpec", "TaskType", "TPUJob", "TPUJobSpec", "TPUPolicy",
    "Model", "ModelSpec", "ModelStatus", "ModelVersion", "ModelVersionSpec",
    "ModelVersionStatus", "Storage", "LocalStorage", "NFSStorage", "GCSStorage",
    "set_defaults_tpujob", "constants",
]
