"""Model / ModelVersion API types.

Analog of /root/reference/apis/model/v1alpha1/{model_types.go,modelversion_types.go}:
a ``Model`` names a trained model and points at its latest version; a
``ModelVersion`` is one trained artifact with a storage binding and an OCI image
build status. Storage adds GCS (TPU-native default on GCP) alongside the
reference's NFS/LocalStorage.
"""
from __future__ import annotations

import datetime as _dt
import enum
from dataclasses import dataclass, field
from typing import List, Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import ObjectMeta


@dataclass
class LocalStorage:
    """hostPath-backed storage pinned to one node
    (reference modelversion_types.go:26-56 / pkg/storage/local_storage.go)."""

    path: str = ""
    node_name: str = ""


@dataclass
class NFSStorage:
    server: str = ""
    path: str = ""
    mounted_path: str = ""


@dataclass
class GCSStorage:
    """GCS bucket storage (new; idiomatic for TPU-on-GKE artifacts)."""

    bucket: str = ""
    prefix: str = ""
    mounted_path: str = ""


@dataclass
class Storage:
    """Tagged union — exactly one provider field set
    (reference Storage struct; provider picked by which field is non-nil,
    pkg/storage/registry/registry.go:36-44)."""

    local_storage: Optional[LocalStorage] = None
    nfs: Optional[NFSStorage] = None
    gcs: Optional[GCSStorage] = None


class ImageBuildPhase(str, enum.Enum):
    BUILDING = "ImageBuilding"
    FAILED = "ImageBuildFailed"
    SUCCEEDED = "ImageBuildSucceeded"


@dataclass
class ModelSpec:
    description: str = ""


@dataclass
class ModelStatus:
    latest_version_name: str = ""
    latest_image: str = ""


@dataclass
class Model:
    api_version: str = f"{constants.API_GROUP}/{constants.API_VERSION}"
    kind: str = constants.KIND_MODEL
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ModelSpec = field(default_factory=ModelSpec)
    status: ModelStatus = field(default_factory=ModelStatus)


@dataclass
class ModelVersionSpec:
    """Reference modelversion_types.go:59-79."""

    model_name: str = ""
    created_by: str = ""  # the TPUJob that produced this artifact
    storage: Storage = field(default_factory=Storage)
    image_repo: str = ""
    image_tag: str = ""


@dataclass
class ModelVersionStatus:
    """Reference modelversion_types.go:83-101."""

    image: str = ""
    image_build_phase: Optional[ImageBuildPhase] = None
    message: str = ""
    finish_time: Optional[_dt.datetime] = None


@dataclass
class ModelVersion:
    api_version: str = f"{constants.API_GROUP}/{constants.API_VERSION}"
    kind: str = constants.KIND_MODELVERSION
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ModelVersionSpec = field(default_factory=ModelVersionSpec)
    status: ModelVersionStatus = field(default_factory=ModelVersionStatus)
