"""ContainerRecreateRequest: the in-place container-restart wire protocol.

Analog of OpenKruise's ``apps.kruise.io/v1alpha1 ContainerRecreateRequest``
exactly as the reference consumes it
(/root/reference/controllers/common/failover.go:210-307 and
/root/reference/controllers/train/elastic_scale.go:342-397): the OPERATOR
posts a CRR naming a pod and its containers, then polls its status; a
NODE-LEVEL agent (the kruise-daemon role — ``client.testing.NodeAgentLoop``
here) watches CRRs, restarts the containers via the container runtime, and
reports the phase. The operator never writes kubelet-owned pod status —
that separation is the whole point of the protocol, and what lets TPU-VM
preemption recovery work on a real cluster.

Lifecycle (mirrors the reference's level-triggered state machine):

* one CRR per pod incarnation, named after the pod, labeled with the pod
  uid (the reference labels job generation; uid is the same idea one level
  tighter — a recreated pod must never be restarted by a stale CRR);
* a stale-label CRR is deleted and re-posted (failover.go:231-237);
* phase ``Failed`` ⇒ the operator falls back to delete+recreate
  (failover.go:242-247); ``Succeeded`` ⇒ the operator deletes the CRR
  (failover.go:258-262 — restarts are repeatable, the name must free up).
"""
from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import List, Optional

from tpu_on_k8s.api.core import ObjectMeta

API_VERSION_CRR = "apps.distributed.tpu.io/v1alpha1"
KIND_CRR = "ContainerRecreateRequest"

# Operator-side label tying a CRR to one pod incarnation.
LABEL_CRR_POD_UID = "apps.distributed.tpu.io/pod-uid"

PHASE_PENDING = "Pending"
PHASE_RECREATING = "Recreating"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"


@dataclass
class ContainerRecreateRequestSpec:
    pod_name: str = ""
    # container names to restart; empty = every container in the pod
    containers: List[str] = field(default_factory=list)
    ordered_recreate: bool = False
    # completed CRRs the operator crashed before collecting are reaped by
    # the node agent after this many seconds (kruise's ttlSecondsAfterFinished)
    ttl_seconds_after_finished: Optional[float] = None


@dataclass
class ContainerRecreateRequestStatus:
    phase: str = PHASE_PENDING
    message: str = ""
    completion_time: Optional[_dt.datetime] = None


@dataclass
class ContainerRecreateRequest:
    api_version: str = API_VERSION_CRR
    kind: str = KIND_CRR
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ContainerRecreateRequestSpec = field(
        default_factory=ContainerRecreateRequestSpec)
    status: ContainerRecreateRequestStatus = field(
        default_factory=ContainerRecreateRequestStatus)


def finished(crr: ContainerRecreateRequest) -> bool:
    return crr.status.phase in (PHASE_SUCCEEDED, PHASE_FAILED)
