"""TPUJob defaulting.

Analog of /root/reference/apis/train/v1alpha1/torchjob_defaults.go:29-197, with the
reference's known defaulting bugs fixed (SURVEY "fidelity notes"):

* ``setDefaults_TorchJobMinMembers`` iterated the (nil) ``MinMembers`` map and so
  never defaulted anything (torchjob_defaults.go:192-197) — here min-members are
  genuinely populated from the task map / slice topology.
"""
from __future__ import annotations

from typing import Dict

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Container, ContainerPort
from tpu_on_k8s.api.types import (
    DAGCondition,
    ElasticPolicy,
    RestartPolicy,
    SchedulingPolicy,
    TaskSpec,
    TaskType,
    TPUJob,
)
from tpu_on_k8s.gang import topology as tpu_topology

_DEFAULT_RESTART = {
    # Master failures are classified by exit code so preemptions retry but user
    # bugs fail fast (reference constants.go:101-110).
    TaskType.MASTER: RestartPolicy.ON_EXIT_CODE,
    TaskType.WORKER: RestartPolicy.ON_FAILURE,
    TaskType.AIMASTER: RestartPolicy.ON_FAILURE,
}


def set_defaults_tpujob(job: TPUJob) -> TPUJob:
    """Mutate ``job`` in place, filling all defaulted fields; returns the job."""
    _normalize_task_keys(job)
    for task_type, task in job.spec.tasks.items():
        if task.num_tasks <= 0:
            task.num_tasks = 1
        if task.restart_policy is None:
            task.restart_policy = _DEFAULT_RESTART[task_type]
        _default_container(task)
        _default_port(task)
    _default_dag_edges(job)
    _default_elastic(job)
    _default_min_members(job)
    return job


def _normalize_task_keys(job: TPUJob) -> None:
    """Case-normalize task-type keys (reference torchjob_defaults.go:33-45).
    Keys may arrive as raw strings from YAML."""
    normalized: Dict[TaskType, TaskSpec] = {}
    for key, task in job.spec.tasks.items():
        tt = key if isinstance(key, TaskType) else TaskType.normalize(str(key))
        normalized[tt] = task
    job.spec.tasks = normalized


def _default_container(task: TaskSpec) -> None:
    spec = task.template.spec
    if not spec.containers:
        spec.containers.append(Container(name=constants.DEFAULT_CONTAINER_NAME))
    for c in spec.containers:
        if not c.name:
            c.name = constants.DEFAULT_CONTAINER_NAME
        if not c.termination_message_policy:
            # Surface the last chunk of logs as the termination message so the
            # failover classifier has context (reference torchjob_defaults.go).
            c.termination_message_policy = "FallbackToLogsOnError"


def _default_port(task: TaskSpec) -> None:
    """Ensure the default container exposes the coordinator port
    (reference torchjob_defaults.go:150-178)."""
    container = task.template.spec.container(constants.DEFAULT_CONTAINER_NAME)
    if container is None:
        container = task.template.spec.containers[0]
    for p in container.ports:
        if p.name == constants.DEFAULT_PORT_NAME:
            return
    container.ports.append(
        ContainerPort(
            name=constants.DEFAULT_PORT_NAME,
            container_port=constants.DEFAULT_COORDINATOR_PORT,
        )
    )


def _default_dag_edges(job: TPUJob) -> None:
    """Inject default DAG edges AIMaster→Master→Worker
    (reference torchjob_defaults.go:95-124): Master waits for AIMaster Running,
    Worker waits for Master Running."""
    tasks = job.spec.tasks
    if TaskType.MASTER in tasks and TaskType.AIMASTER in tasks:
        if not tasks[TaskType.MASTER].dag_conditions:
            tasks[TaskType.MASTER].dag_conditions = [
                DAGCondition(upstream=TaskType.AIMASTER, on_phase="Running")
            ]
    if TaskType.WORKER in tasks:
        upstream = (
            TaskType.MASTER
            if TaskType.MASTER in tasks
            else (TaskType.AIMASTER if TaskType.AIMASTER in tasks else None)
        )
        if upstream is not None and not tasks[TaskType.WORKER].dag_conditions:
            tasks[TaskType.WORKER].dag_conditions = [
                DAGCondition(upstream=upstream, on_phase="Running")
            ]


def _default_elastic(job: TPUJob) -> None:
    """Clamp elastic bounds and worker count — snapped to slice-legal host
    quanta (the ElasticPolicy contract in types.py): e.g. min=3 on v5e becomes
    4, because no 3-host v5e topology exists."""
    ep = job.spec.elastic_policy
    if ep is None:
        return
    acc = job.spec.tpu_policy.accelerator
    ep.min_replicas = tpu_topology.snap_host_count(acc, max(ep.min_replicas, 1))
    if ep.max_replicas < ep.min_replicas:
        ep.max_replicas = ep.min_replicas
    else:
        # Largest legal quantum not exceeding the requested max.
        legal = [c for c in tpu_topology.legal_host_counts(acc)
                 if ep.min_replicas <= c <= ep.max_replicas]
        ep.max_replicas = legal[-1] if legal else ep.min_replicas
    worker = job.spec.tasks.get(TaskType.WORKER)
    if worker is not None:
        clamped = min(max(worker.num_tasks, ep.min_replicas), ep.max_replicas)
        worker.num_tasks = tpu_topology.snap_host_count(acc, clamped)
    if ep.nproc_per_node <= 0:
        # On TPU a "proc" is one host process driving that host's chips.
        ep.nproc_per_node = 1
    if not ep.rendezvous_backend:
        ep.rendezvous_backend = "xla"


def _default_min_members(job: TPUJob) -> None:
    """Populate SchedulingPolicy.min_members for every task type (fixing the
    reference's no-op, torchjob_defaults.go:192-197). The TPU rule: a slice is
    allocated atomically, so a task type whose pods form a slice defaults
    MinMember to the slice host count (SURVEY §2.8 TPU equivalent), while
    auxiliary types default to their full replica count."""
    policy = job.spec.run_policy.scheduling_policy
    if policy is None:
        policy = SchedulingPolicy()
        job.spec.run_policy.scheduling_policy = policy
    for task_type, task in job.spec.tasks.items():
        if task_type in policy.min_members:
            continue
        # TPU slices are allocated atomically and every task pod is a slice
        # host, so a partial gang is never useful: the gang floor is the full
        # replica count (covers num_slices > 1, where workers span all slices).
        policy.min_members[task_type] = task.num_tasks
