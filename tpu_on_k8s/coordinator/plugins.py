"""Coordinator plugins: quota (tenant + filter + pre-dequeue) and priority.

Analog of /root/reference/pkg/coordinator/plugins/{quota.go,priority.go,
registry.go}. The quota plugin's *assumed quota* mechanism (quota.go:176-277)
carries over: a reservation is taken at pre-dequeue so back-to-back scheduling
cycles don't over-admit before the dequeued job's pods land in
``ResourceQuota.status.used``; reservations expire after a TTL or when the
coordinator observes the job leaving the queued state (``release``).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from tpu_on_k8s.api.core import PriorityClass, ResourceQuota
from tpu_on_k8s.client.cluster import InMemoryCluster
from tpu_on_k8s.coordinator.types import QueueUnit, Status
from tpu_on_k8s.utils import resources as resmath

DEFAULT_ASSUME_TTL_SECONDS = 60.0  # quota.go:48


class QuotaPlugin:
    """Tenant + Filter + PreDequeue plugin (quota.go)."""

    name = "Quota"

    def __init__(self, cluster: InMemoryCluster, *,
                 assume_ttl_seconds: float = DEFAULT_ASSUME_TTL_SECONDS,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.cluster = cluster
        self.assume_ttl = assume_ttl_seconds
        self._clock = clock
        self._lock = threading.Lock()
        # uid → (tenant, resources, assumed-at)
        self._assumed: Dict[str, Tuple[str, Dict[str, float], float]] = {}

    # ---- TenantPlugin ---------------------------------------------------------
    def tenant_name(self, unit: QueueUnit) -> str:
        """SchedulingPolicy.Queue else namespace (quota.go:82-92)."""
        policy = unit.scheduling_policy
        if policy is not None and policy.queue:
            return policy.queue
        return unit.job.metadata.namespace

    # ---- FilterPlugin ---------------------------------------------------------
    def filter(self, unit: QueueUnit) -> Status:
        """Wait while the unit's request exceeds namespace quota minus assumed
        reservations (quota.go:97-131). Namespaces without any ResourceQuota
        are unlimited."""
        quotas = self.cluster.list(ResourceQuota, unit.job.metadata.namespace)
        if not quotas:
            return Status.success()
        hard: Dict[str, float] = {}
        used: Dict[str, float] = {}
        for q in quotas:
            hard = resmath.add(hard, q.spec.hard)
            used = resmath.add(used, q.status.used)
        available = resmath.subtract(hard, used)
        for _, res, _ in self._live_assumed(unit.job.metadata.namespace):
            available = resmath.subtract(available, res)
        if not resmath.fits(unit.resources, available):
            return Status.wait(
                f"quota exceeded in namespace {unit.job.metadata.namespace}: "
                f"request {unit.resources} > available {available}")
        return Status.success()

    # ---- PreDequeuePlugin -----------------------------------------------------
    def pre_dequeue(self, unit: QueueUnit) -> Status:
        """Optimistically reserve the unit's request (quota.go:176-181)."""
        with self._lock:
            self._assumed[unit.uid] = (
                unit.job.metadata.namespace, dict(unit.resources), self._clock())
        return Status.success()

    # ---- reservation lifecycle ------------------------------------------------
    def release(self, uid: str) -> None:
        """Drop a reservation once the job's usage is visible in quota status
        or the job left the queued state (quota.go:256-277)."""
        with self._lock:
            self._assumed.pop(uid, None)

    def _live_assumed(self, namespace: str) -> List[Tuple[str, Dict[str, float], float]]:
        now = self._clock()
        with self._lock:
            expired = [uid for uid, (_, _, at) in self._assumed.items()
                       if now - at > self.assume_ttl]
            for uid in expired:
                del self._assumed[uid]
            return [(uid, res, at) for uid, (ns, res, at) in self._assumed.items()
                    if ns == namespace]

    def assumed_count(self) -> int:
        with self._lock:
            return len(self._assumed)


class PriorityPlugin:
    """Score = SchedulingPolicy.Priority, else the PriorityClass value, else 0
    (priority.go:48-87)."""

    name = "Priority"

    def __init__(self, cluster: InMemoryCluster) -> None:
        self.cluster = cluster

    def score(self, unit: QueueUnit) -> float:
        if unit.priority is not None:
            return float(unit.priority)
        policy = unit.scheduling_policy
        if policy is not None and policy.priority_class_name:
            pc = self.cluster.try_get(PriorityClass, "", policy.priority_class_name)
            if pc is not None:
                return float(pc.value)
        return 0.0


@dataclass
class PluginConfig:
    """Default wiring (reference plugins/registry.go:36-49): Tenant=Quota,
    Filter=[Quota], Score=[Priority], PreDequeue=[Quota]."""

    tenant: object = None
    pre_filters: List[object] = None
    filters: List[object] = None
    scorers: List[object] = None
    pre_dequeues: List[object] = None

    @classmethod
    def default(cls, cluster: InMemoryCluster, *,
                assume_ttl_seconds: float = DEFAULT_ASSUME_TTL_SECONDS,
                clock: Callable[[], float] = time.monotonic) -> "PluginConfig":
        quota = QuotaPlugin(cluster, assume_ttl_seconds=assume_ttl_seconds, clock=clock)
        return cls(
            tenant=quota,
            pre_filters=[],
            filters=[quota],
            scorers=[PriorityPlugin(cluster)],
            pre_dequeues=[quota],
        )
