"""Job coordinator (L4): multi-tenant queueing + plugin-driven admission.

Analog of /root/reference/pkg/coordinator/ (SURVEY §2.7). On TPU, tenant queues
double as the multi-slice coordination surface: each queue maps to a slice pool
and the smooth-WRR selector apportions dequeues across pools (BASELINE.md's
"two WRR-coordinated queues on multi-slice v5e").

`broker` adds the chip-capacity layer UNDER the queues: one slice market
every consumer — serving fleets, elastic training, the warm floor, and
the preemptible batch lane — bids on, cleared each tick with a
degrade-before-take escalation ladder and every grant/preempt/refusal
on the decision ledger.
"""

from tpu_on_k8s.coordinator.broker import (
    KIND_BATCH,
    KIND_SERVING,
    KIND_TRAINING,
    KIND_WARM,
    PRIORITY_BATCH,
    PRIORITY_SERVING,
    PRIORITY_TRAINING,
    PRIORITY_WARM,
    Bid,
    CapacityBroker,
)
from tpu_on_k8s.coordinator.core import (
    DEFAULT_SCHEDULING_PERIOD_SECONDS,
    Coordinator,
)
from tpu_on_k8s.coordinator.plugins import (
    PluginConfig,
    PriorityPlugin,
    QuotaPlugin,
)
from tpu_on_k8s.coordinator.policy import (
    RoundRobinSelector,
    SmoothWeightedRoundRobinSelector,
)
from tpu_on_k8s.coordinator.queue import Queue
from tpu_on_k8s.coordinator.types import Code, QueueUnit, Status
