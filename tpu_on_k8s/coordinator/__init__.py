"""Job coordinator (L4): multi-tenant queueing + plugin-driven admission.

Analog of /root/reference/pkg/coordinator/ (SURVEY §2.7). On TPU, tenant queues
double as the multi-slice coordination surface: each queue maps to a slice pool
and the smooth-WRR selector apportions dequeues across pools (BASELINE.md's
"two WRR-coordinated queues on multi-slice v5e").
"""

from tpu_on_k8s.coordinator.core import (
    DEFAULT_SCHEDULING_PERIOD_SECONDS,
    Coordinator,
)
from tpu_on_k8s.coordinator.plugins import (
    PluginConfig,
    PriorityPlugin,
    QuotaPlugin,
)
from tpu_on_k8s.coordinator.policy import (
    RoundRobinSelector,
    SmoothWeightedRoundRobinSelector,
)
from tpu_on_k8s.coordinator.queue import Queue
from tpu_on_k8s.coordinator.types import Code, QueueUnit, Status
