"""Capacity broker: one slice market, every control loop a bidder.

Five loops close independently — the ElasticAutoscaler over TPUJobs, the
FleetAutoscaler over InferenceServices (service + per-pool recommenders),
and the SLO-paged scale-ups riding them — and until now nothing
arbitrated when the cluster was full: a serving burst and an elastic
training job could deadlock on the same slices with no ledgered
resolution. The broker is that arbiter, built as one more
`controller/loopkernel.LoopKernel` loop so every grant, preemption,
refusal, and degrade lands on the same `obs/ledger.DecisionLedger` and
`tools/why_report.py` can answer "who took my chips".

**The market.** Every consumer registers a ``bid_fn`` returning a
:class:`Bid` — priority, current grant, desired grant, un-harvestable
floor, chips per allocation unit, marginal utility, and preemption cost
(the allocation shape from "An Optimal Resource Allocator of Elastic
Training", PAPERS.md). Two consumer styles:

* **self-scaling** (serving fleets, elastic training): they execute
  their own patches and PULL admission through the synchronous
  :meth:`CapacityBroker.request_capacity` gate *before* patching. A
  grant reserves the chips until the consumer's bid reflects them; a
  refusal registers a pressure episode the tick loop works to relieve.
  The refused caller ledgers ``conflict:BrokerRefused`` on its own loop
  and — by construction, because the gate sits before the patch — burns
  no cooldown (the same no-burn rule as a failed patch).
* **broker-managed** (the batch/offline inference lane, a `min_warm`
  headroom lane): the broker PUSHes both growth (the fill phase grants
  them idle chips) and shrink (harvest) through their ``apply_fn``.

**The escalation ladder.** Under pressure (a refused request), each
tick climbs, in order: (1) *degrade-before-take* — flip the pressured
fleet to a cheaper `DecodePolicy` variant (int8, lower spec_k; Rubick's
reconfigurability argument, PAPERS.md) once per episode; (2) *harvest*
the batch/warm lanes — they yield within one tick of a page; (3)
*shrink* elastic training toward its floor via live reshard (PR 12:
4.3s pause, abort ⇒ checkpoint-restart, never corruption); (4) only
then *refuse* with a typed, ledgered reason. Freed capacity is granted
two-phase: victims shrink this tick, the requester's grant lands when
its next ``request_capacity`` sees the freed chips in the victims'
bids — the broker never promises chips that are still occupied.

Every lane transition opens an effect horizon (closed when the lane's
bid reflects the committed target) and the grant/apply path is
chaos-injectable at ``SITE_BROKER_GRANT`` (stale-bid and write-conflict
faults): a faulted apply rejects the WHOLE transition — no partial
apply, the reservation is dropped, and the market re-clears from fresh
bids next tick.

Deterministic by construction: clearing iterates sorted names, takes no
wall clock (the tick period comes from the caller's scheduler), and the
twin drives ``run_once`` from its virtual clock — two seeded runs
produce byte-identical ledgers (`make broker-soak`).
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from tpu_on_k8s import chaos
from tpu_on_k8s.autoscale.policy import ACTION_DOWN, ACTION_UP, Decision
from tpu_on_k8s.controller.loopkernel import (
    ACTION_HOLD,
    LoopKernel,
    OpenHorizon,
    format_commit_failure_line,
    format_decision_line,
)
from tpu_on_k8s.obs.ledger import (
    COMMIT_LANDED,
    HORIZON_REPLICAS_READY,
    HORIZON_ROLLOUT_COMPLETE,
)

_log = logging.getLogger(__name__)

#: lane action for the degrade-before-take pressure valve (rung 1):
#: the lane keeps its chips but flips to a cheaper DecodePolicy variant
ACTION_DEGRADE = "degrade"

#: consumer kinds (victim reasons distinguish harvest vs preempt by kind)
KIND_SERVING = "serving"
KIND_TRAINING = "training"
KIND_BATCH = "batch"
KIND_WARM = "warm"

#: default priorities — strict ordering, higher outbids lower
PRIORITY_SERVING = 100
PRIORITY_WARM = 80
PRIORITY_TRAINING = 50
PRIORITY_BATCH = 10


@dataclasses.dataclass(frozen=True)
class Bid:
    """One consumer's standing bid. ``current``/``desired``/``floor``
    are in allocation units (replicas, hosts, batch slots); ``unit`` is
    chips per allocation unit — the market clears in chips but moves
    whole units. ``floor`` units can never be harvested (a training
    job's minimum gang, a fleet's min_replicas). ``marginal_utility``
    and ``preemption_cost`` break ties among equal-priority victims:
    the cheapest-to-preempt, least-useful chip goes first."""

    name: str
    kind: str
    priority: int
    current: int
    desired: int
    floor: int = 0
    unit: int = 1
    marginal_utility: float = 0.0
    preemption_cost: float = 0.0


@dataclasses.dataclass
class _Grant:
    """A reservation from `request_capacity`: chips promised to a
    self-scaling consumer whose bid does not yet reflect them. Retired
    when the bid catches up; revoked (ledgered) when it never does."""

    target_units: int
    trigger: str = ""
    urgent: bool = False
    ledgered: bool = False
    ticks: int = 0
    #: the lane's holding when the grant was admitted — what the
    #: announcement's ``grant:+N`` delta is measured from
    base_units: int = 0


@dataclasses.dataclass
class _Pressure:
    """One refused requester's open pressure episode: how many more
    units it wanted, whether an SLO page backs it, the trigger string
    its preemptions inherit, and the ladder state (``degraded`` — rung
    1 fires once per episode). ``fresh`` is re-armed by every refused
    request; an episode nobody refreshes lapses instead of preempting
    on behalf of a requester that stopped asking."""

    units: int
    urgent: bool = False
    trigger: str = ""
    degraded: bool = False
    ticks: int = 0
    idle: int = 0
    fresh: bool = True


@dataclasses.dataclass
class _LanePack:
    """One lane's cleared allocation for this tick. ``apply`` marks
    transitions the broker itself must push through the consumer's
    ``apply_fn``/``degrade_fn`` (harvest, fill, degrade); grant
    announcements are acknowledgements of a patch the requester
    executes itself."""

    bid: Bid
    action: str
    target: int
    reason: str
    trigger: str = ""
    apply: bool = False


@dataclasses.dataclass
class _Consumer:
    name: str
    bid_fn: Callable[[], Optional[Bid]]
    apply_fn: Optional[Callable[[int, str], bool]] = None
    degrade_fn: Optional[Callable[[bool], str]] = None
    managed: bool = False
    lane: Optional["_LaneState"] = None


class _LaneState(LoopKernel):
    """One consumer's slice of the market, as a LoopKernel: the broker
    clears the whole market in ``run_once`` and then drives one tick
    per lane, so every lane transition is one ledger record on loop
    ``broker/<consumer>`` with the standard horizon machinery. Lane
    state is touched ONLY by the broker tick (single thread): the
    synchronous admission gate never writes here — grants are announced
    on the next tick."""

    owner: Optional["CapacityBroker"] = None
    consumer: Optional[_Consumer] = None

    def observe(self, ctx):
        self.seq += 1
        return ctx["pack"]

    def decide(self, pack, ctx):
        decision = Decision(self.seq, pack.action, pack.bid.current,
                            pack.target, pack.reason)
        return decision

    def actionable(self, decision, ctx) -> bool:
        if ctx["pack"].apply:
            return True
        return super().actionable(decision, ctx)

    def commit(self, pack, decision, ctx) -> str:
        c = self.consumer
        fault, fseq = chaos.fire_seq(chaos.SITE_BROKER_GRANT,
                                     consumer=c.name,
                                     action=decision.action,
                                     target=decision.target)
        if fault is not None:
            ctx["chaos_seq"] = fseq
            failure = type(fault.to_exception()).__name__
            self.owner._lane_failed(c.name, decision, failure)
            return f"conflict:{failure}"
        if decision.action == ACTION_UP and not pack.apply:
            # grant acknowledgement: the requester executes its own
            # patch — the broker's commit is the reservation itself
            self.owner._grant_ledgered(c.name)
            return COMMIT_LANDED
        if decision.action == ACTION_DEGRADE:
            variant = c.degrade_fn(True) if c.degrade_fn is not None else ""
            if not variant:
                self.owner._lane_failed(c.name, decision,
                                        "DegradeExhausted")
                return "conflict:DegradeExhausted"
            return COMMIT_LANDED
        ok = bool(c.apply_fn(decision.target, decision.reason)) \
            if c.apply_fn is not None else False
        if not ok:
            self.owner._lane_failed(c.name, decision, "ApplyFailed")
            return "conflict:ApplyFailed"
        return COMMIT_LANDED

    def record(self, pack, decision, ctx) -> None:
        self.owner._record_lane(self.consumer.name, decision)

    def tick_of(self, pack) -> int:
        return self.seq

    def trigger_of(self, pack, ctx) -> str:
        fseq = ctx.get("chaos_seq")
        if fseq:
            return f"chaos#{fseq}"
        return pack.trigger

    def signals_of(self, pack) -> Tuple[Tuple[str, str], ...]:
        b = pack.bid
        return (("priority", str(b.priority)),
                ("desired", str(b.desired)),
                ("unit", str(b.unit)))

    def horizon_events(self, horizon: OpenHorizon, pack, ctx):
        if horizon.action == ACTION_DEGRADE:
            # the policy flip is pushed synchronously at commit; the
            # next observed tick proves the lane survived it
            return ((HORIZON_ROLLOUT_COMPLETE, True),)
        if horizon.action == ACTION_UP \
                and pack.bid.current >= horizon.target:
            return ((HORIZON_REPLICAS_READY, True),)
        if horizon.action == ACTION_DOWN \
                and pack.bid.current <= horizon.target:
            return ((HORIZON_REPLICAS_READY, True),)
        return ()


class CapacityBroker:
    """The slice market (see module doc). ``capacity_chips`` is the one
    budget every consumer bids against; ``<= 0`` disables arbitration
    (every request admitted, no lanes ticked — the pre-broker
    behavior). ``metrics`` is an optional `metrics.BrokerMetrics`."""

    def __init__(self, capacity_chips: int, *, ledger=None, metrics=None,
                 period_s: float = 10.0, max_pressure_ticks: int = 8,
                 max_grant_ticks: int = 8) -> None:
        self.capacity = capacity_chips
        self.ledger = ledger
        self.metrics = metrics
        self.period_s = period_s
        self.max_pressure_ticks = max_pressure_ticks
        self.max_grant_ticks = max_grant_ticks
        self.tick = 0
        self.tick_errors = 0
        #: the broker's own decision log — one `format_decision_line`
        #: per lane tick (scope ``lane=<consumer>``), byte-compared by
        #: `tools/broker_soak.py`
        self.decision_log: List[str] = []
        self._lock = threading.Lock()
        self._consumers: Dict[str, _Consumer] = {}
        self._grants: Dict[str, _Grant] = {}
        self._pressure: Dict[str, _Pressure] = {}
        self._last_bids: Dict[str, Bid] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- registration
    def register(self, name: str, bid_fn: Callable[[], Optional[Bid]], *,
                 apply_fn: Optional[Callable[[int, str], bool]] = None,
                 degrade_fn: Optional[Callable[[bool], str]] = None,
                 managed: bool = False) -> None:
        """Register a consumer. ``bid_fn`` returns the lane's standing
        :class:`Bid` (None = not participating this tick). ``apply_fn
        (target_units, reason) -> bool`` executes a broker-pushed
        resize (harvest always; growth too when ``managed``).
        ``degrade_fn(apply) -> variant`` is the rung-1 valve: with
        ``apply=False`` it peeks the next cheaper variant without
        flipping; with ``apply=True`` it flips and returns the variant
        ('' when nothing is left to flip)."""
        lane = _LaneState(f"broker/{name}", ledger=self.ledger)
        lane.owner = self
        c = _Consumer(name=name, bid_fn=bid_fn, apply_fn=apply_fn,
                      degrade_fn=degrade_fn, managed=managed, lane=lane)
        lane.consumer = c
        with self._lock:
            self._consumers[name] = c

    def deregister(self, name: str) -> None:
        with self._lock:
            c = self._consumers.pop(name, None)
            self._grants.pop(name, None)
            self._pressure.pop(name, None)
            self._last_bids.pop(name, None)
        if c is not None and c.lane is not None:
            c.lane.abandon()

    def consumers(self) -> List[str]:
        with self._lock:
            return sorted(self._consumers)

    # ------------------------------------------------------- admission gate
    def request_capacity(self, name: str, current: int, target: int, *,
                         urgent: bool = False, trigger: str = "") -> bool:
        """The synchronous admission gate self-scaling consumers call
        BEFORE patching a scale-up. True = admitted (the chips are
        reserved until the consumer's bid reflects them); False =
        refused — the caller must not patch (and must not burn a
        cooldown), and a pressure episode now works the escalation
        ladder on its behalf. Unregistered consumers and shrinks are
        always admitted (opt-in semantics). ``trigger`` is the caller's
        provenance ref (``slo_page:<svc>#N``) — every preemption made
        on this requester's behalf inherits it, so `why_report`
        resolves the eviction to its cause."""
        if self.capacity <= 0 or target <= current:
            return True
        with self._lock:
            if name not in self._consumers:
                return True
            # delta semantics: the caller's (current, target) may be a
            # sub-view of the lane (a pool of a disaggregated service);
            # the request is for `target - current` MORE units on top of
            # whatever the lane's bid already holds
            b = self._last_bids.get(name)
            unit = b.unit if b is not None else 1
            base = b.current if b is not None else current
            expected = base + (target - current)
            g = self._grants.get(name)
            if g is not None and g.target_units >= expected:
                return True                       # already reserved
            held = max(base, g.target_units) if g is not None else base
            free = self.capacity - self._used_chips_locked()
            if (expected - held) * unit <= free:
                self._grants[name] = _Grant(target_units=expected,
                                            trigger=trigger, urgent=urgent,
                                            base_units=held)
                self._pressure.pop(name, None)
                self._inc("grants")
                return True
            p = self._pressure.get(name)
            units = expected - held
            if p is None:
                self._pressure[name] = _Pressure(
                    units=units, urgent=urgent, trigger=trigger)
            else:
                p.units = max(p.units, units)
                p.urgent = p.urgent or urgent
                p.trigger = trigger or p.trigger
                p.fresh = True
            self._inc("refusals")
            return False

    # ------------------------------------------------------------ the tick
    def run_once(self) -> None:
        """One market clearing: gather bids, work the pressure ladder,
        fill idle capacity into managed lanes, then drive one
        LoopKernel tick per lane. Consumer callbacks (bids, degrade
        peeks, applies) all run OUTSIDE the broker lock."""
        if self.capacity <= 0:
            return
        with self._lock:
            consumers = [self._consumers[k] for k in sorted(self._consumers)]
        bids: Dict[str, Bid] = {}
        for c in consumers:
            b = c.bid_fn()
            if b is not None:
                bids[c.name] = b
        with self._lock:
            self.tick += 1
            self._last_bids = dict(bids)
            plan, degrades, expired = self._clear_locked(bids)
            free = self.capacity - self._used_chips_locked()
            n_pressure = len(self._pressure)
        for name, trigger in degrades:
            c = self._consumer(name)
            variant = c.degrade_fn(False) \
                if c is not None and c.degrade_fn is not None else ""
            if variant:
                b = bids[name]
                plan[name] = _LanePack(
                    bid=b, action=ACTION_DEGRADE, target=b.current,
                    reason=f"degrade:{variant}", trigger=trigger,
                    apply=True)
        for c in consumers:
            pack = plan.get(c.name)
            if pack is None or c.lane is None:
                continue
            if c.name in expired:
                c.lane.abandon()
            c.lane.run_tick({"pack": pack})
        self._set_gauge("free_chips", max(0, free))
        self._set_gauge("pressure_lanes", n_pressure)
        self._set_gauge("capacity_chips", self.capacity)

    def run(self) -> threading.Thread:
        """Start the broker's tick thread (daemon — same lifecycle
        pattern as the autoscalers)."""
        t = threading.Thread(target=self._run, name="capacity-broker",
                             daemon=True)
        self._thread = t
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)

    def tick_count(self) -> int:
        """Clearings run so far — locked so readers on other threads
        (the twin summary, dashboards) never race the tick."""
        with self._lock:
            return self.tick

    def decision_lines(self) -> List[str]:
        """A point-in-time copy of the broker's decision log, locked
        against concurrent lane commits."""
        with self._lock:
            return list(self.decision_log)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.run_once()
            except Exception:
                # same discipline as the autoscaler loops: a crashing
                # clearing surfaces in the log AND a counter, never
                # dies silently
                _log.exception("capacity broker tick failed")
                self.tick_errors += 1
                if self.metrics is not None:
                    self.metrics.inc("tick_errors")

    # ------------------------------------------------------------- clearing
    def _clear_locked(self, bids: Dict[str, Bid]):
        """Pure clearing under the lock: no consumer code runs here.
        Returns (lane packs, degrade candidates, lanes whose expired
        grant horizon must be abandoned)."""
        plan: Dict[str, _LanePack] = {}
        degrades: List[Tuple[str, str]] = []
        expired: Set[str] = set()
        for name in sorted(bids):
            b = bids[name]
            plan[name] = _LanePack(bid=b, action=ACTION_HOLD,
                                   target=b.current, reason="steady")
        self._advance_grants_locked(bids, plan, expired)
        free = self.capacity - self._used_chips_locked()
        free_remaining = max(0, free)
        cuts: Dict[str, int] = {}
        order = sorted(
            self._pressure,
            key=lambda n: (-int(self._pressure[n].urgent),
                           -(bids[n].priority if n in bids else 0), n))
        for name in order:
            free_remaining = self._ladder_locked(
                name, bids, plan, degrades, cuts, free_remaining)
        if not self._pressure and free_remaining > 0:
            self._fill_locked(bids, plan, cuts, free_remaining)
        return plan, degrades, expired

    def _advance_grants_locked(self, bids, plan, expired) -> None:
        for name in sorted(self._grants):
            g = self._grants[name]
            b = bids.get(name)
            if b is None:
                continue                   # not bidding yet — hold the chips
            if b.current >= g.target_units:
                del self._grants[name]     # satisfied: the bid carries it now
                if not g.ledgered:
                    # the requester scaled into its grant before the
                    # lane could announce it — still land one ledgered
                    # acknowledgment, so "who got the chips" always has
                    # a record carrying the requester's trigger
                    plan[name] = _LanePack(
                        bid=b, action=ACTION_UP, target=g.target_units,
                        reason=(f"grant:"
                                f"+{g.target_units - g.base_units}"),
                        trigger=g.trigger)
                continue
            if not g.ledgered:
                plan[name] = _LanePack(
                    bid=b, action=ACTION_UP, target=g.target_units,
                    reason=f"grant:+{g.target_units - b.current}",
                    trigger=g.trigger)
                continue
            g.ticks += 1
            if g.ticks > self.max_grant_ticks:
                # the requester never scaled into its reservation (its
                # patch lost, the object vanished): release the chips
                del self._grants[name]
                expired.add(name)
                self._inc("grant_expired")
                plan[name] = _LanePack(bid=b, action=ACTION_HOLD,
                                       target=b.current,
                                       reason="grant_expired")

    def _ladder_locked(self, name, bids, plan, degrades, cuts,
                       free_remaining: int) -> int:
        """One pressure episode's tick of the escalation ladder:
        degrade → harvest → shrink → refuse. Returns the free chips
        left unclaimed for lower-priority episodes."""
        p = self._pressure[name]
        b = bids.get(name)
        if b is None:
            del self._pressure[name]
            return free_remaining
        if p.fresh:
            p.fresh = False
            p.idle = 0
        else:
            p.idle += 1
            if p.idle >= 2:
                # the requester stopped asking (burst over, degrade
                # worked): lapse quietly rather than evict for nobody
                del self._pressure[name]
                plan[name] = _LanePack(bid=b, action=ACTION_HOLD,
                                       target=b.current,
                                       reason="pressure_lapsed",
                                       trigger=p.trigger)
                return free_remaining
        p.ticks += 1
        needed = p.units * b.unit
        if needed <= free_remaining:
            del self._pressure[name]
            plan[name] = _LanePack(bid=b, action=ACTION_HOLD,
                                   target=b.current,
                                   reason="pressure_relieved",
                                   trigger=p.trigger)
            return free_remaining - needed
        if p.ticks > self.max_pressure_ticks:
            del self._pressure[name]
            plan[name] = _LanePack(
                bid=b, action=ACTION_HOLD, target=b.current,
                reason=f"refuse:pressure_timeout need={p.units}",
                trigger=p.trigger)
            self._inc("refuse_final")
            return free_remaining
        want_degrade = False
        if not p.degraded:
            c = self._consumers.get(name)
            if c is not None and c.degrade_fn is not None:
                p.degraded = True
                want_degrade = True
                degrades.append((name, p.trigger))
                self._inc("degrades")
        shortfall = needed - free_remaining
        victims = [v for v in sorted(bids)
                   if v != name and bids[v].priority < b.priority
                   and v not in self._pressure and v not in self._grants]
        victims.sort(key=lambda v: (bids[v].priority,
                                    bids[v].preemption_cost,
                                    bids[v].marginal_utility, v))
        planned: List[Tuple[str, int]] = []
        remaining = shortfall
        for v in victims:
            if remaining <= 0:
                break
            vb = bids[v]
            avail = vb.current - max(vb.floor, 0) - cuts.get(v, 0)
            if avail <= 0:
                continue
            take = min(avail, -(-remaining // vb.unit))
            planned.append((v, take))
            remaining -= take * vb.unit
        if remaining > 0:
            # rung 4 — unless rung 1 just fired: a degrade deserves one
            # tick to relieve the load before the refusal is final
            if not want_degrade:
                del self._pressure[name]
                plan[name] = _LanePack(
                    bid=b, action=ACTION_HOLD, target=b.current,
                    reason=f"refuse:capacity_exhausted short={remaining}",
                    trigger=p.trigger)
                self._inc("refuse_final")
            return free_remaining
        for v, take in planned:
            cuts[v] = cuts.get(v, 0) + take
            vb = bids[v]
            verb = "preempt" if vb.kind == KIND_TRAINING else "harvest"
            plan[v] = _LanePack(
                bid=vb, action=ACTION_DOWN,
                target=vb.current - cuts[v],
                reason=f"{verb}:{name}", trigger=p.trigger, apply=True)
            if verb == "preempt":
                self._inc("preempts")
            else:
                self._inc("harvests")
        if not want_degrade:
            plan[name] = _LanePack(
                bid=b, action=ACTION_HOLD, target=b.current,
                reason=f"pressure_wait short={shortfall}",
                trigger=p.trigger)
        return 0

    def _fill_locked(self, bids, plan, cuts, free_remaining: int) -> None:
        """No pressure anywhere: idle chips flow to broker-managed
        lanes (the batch lane harvesting idle decode capacity) by
        priority."""
        managed = [n for n in bids
                   if (c := self._consumers.get(n)) is not None
                   and c.managed and n not in cuts]
        managed.sort(key=lambda n: (-bids[n].priority, n))
        for name in managed:
            if free_remaining <= 0:
                break
            b = bids[name]
            want = b.desired - b.current
            if want <= 0:
                continue
            units = min(want, free_remaining // b.unit)
            if units <= 0:
                continue
            plan[name] = _LanePack(bid=b, action=ACTION_UP,
                                   target=b.current + units,
                                   reason="fill:idle_capacity", apply=True)
            # earmark the filled chips as a (pre-ledgered) reservation:
            # until the lane's NEXT bid reflects the push, admission
            # through ``request_capacity`` must already see them as
            # used — without this, a scale-up landing between the fill
            # and the bid catching up overcommits the market
            self._grants[name] = _Grant(target_units=b.current + units,
                                        ledgered=True)
            free_remaining -= units * b.unit
            self._inc("fills")

    # ------------------------------------------------------------- plumbing
    def _used_chips_locked(self) -> int:
        used = 0
        for name, b in self._last_bids.items():
            g = self._grants.get(name)
            held = max(b.current, g.target_units if g is not None else 0)
            used += held * b.unit
        for name, g in self._grants.items():
            if name not in self._last_bids:
                used += g.target_units
        return used

    def _consumer(self, name: str) -> Optional[_Consumer]:
        with self._lock:
            return self._consumers.get(name)

    def _grant_ledgered(self, name: str) -> None:
        with self._lock:
            g = self._grants.get(name)
            if g is not None:
                g.ledgered = True

    def _lane_failed(self, name: str, decision, failure: str) -> None:
        """A lane commit was rejected (chaos stale-bid/conflict, an
        apply that returned False): drop any reservation the decision
        was acknowledging — the market re-clears from fresh bids next
        tick, no partial apply."""
        with self._lock:
            if decision.action == ACTION_UP:
                self._grants.pop(name, None)
            self.decision_log.append(format_commit_failure_line(
                decision.seq, failure, scope=(("lane", name),)))
        self._inc("lane_conflicts")

    def _record_lane(self, name: str, decision) -> None:
        with self._lock:
            self.decision_log.append(format_decision_line(
                decision.seq, decision.action, decision.current,
                decision.target, decision.reason, scope=(("lane", name),)))

    def _inc(self, counter: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(counter)

    def _set_gauge(self, name: str, value: float) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge(name, value)
