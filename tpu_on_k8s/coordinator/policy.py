"""Queue-selection policies: round-robin and smooth weighted round-robin.

Analog of /root/reference/pkg/coordinator/core/policy.go — RoundRobin (:31-76)
and WeightedRoundRobin (:80-230, the classic nginx gcd/maxWeight scan). Two
deliberate upgrades over the reference:

* WRR is actually wired in as the default (the reference built it but left
  plain RR in the ctor — coordinator.go:62, SURVEY §2.7 note);
* the weighted variant is *smooth* WRR (the reference's own TODO at
  policy.go:232): each pick adds weight to a running current-weight and picks
  the max, so a {5,1,1} weighting yields a-b-a-a-c-a-a instead of bursts.

Weight = total pending task count in the queue (calculateQueueWeight,
policy.go:224-230), recomputed every pick so weights track queue churn.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Protocol

from tpu_on_k8s.coordinator.queue import Queue


class QueueSelector(Protocol):
    def next(self, queues: List[Queue]) -> Optional[Queue]: ...


class SmoothWRR:
    """The smooth-WRR core (nginx algorithm), detached from ``Queue`` so the
    serving gateway's tenant scheduler (`tpu_on_k8s/serve/scheduler.py`) can
    reuse the exact policy the coordinator runs: each pick adds every
    candidate's weight to its running current-weight, picks the max, then
    subtracts the total from the winner — a {5,1,1} weighting yields
    a-b-a-a-c-a-a instead of bursts. State for vanished keys is dropped so
    a departed tenant's debt doesn't linger. NOT thread-safe; callers hold
    their own lock (both users already do)."""

    def __init__(self) -> None:
        self._current: Dict[str, float] = {}

    def pick(self, weights: Dict[str, float]) -> Optional[str]:
        if not weights:
            return None
        total = sum(weights.values())
        self._current = {k: v for k, v in self._current.items()
                         if k in weights}
        best: Optional[str] = None
        for key in sorted(weights):
            cur = self._current.get(key, 0.0) + weights[key]
            self._current[key] = cur
            if best is None or cur > self._current[best]:
                best = key
        self._current[best] -= total
        return best


class RoundRobinSelector:
    """Plain RR over queue names (policy.go:31-76)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last: Optional[str] = None

    def next(self, queues: List[Queue]) -> Optional[Queue]:
        candidates = [q for q in queues if len(q) > 0]
        if not candidates:
            return None
        candidates.sort(key=lambda q: q.name)
        with self._lock:
            names = [q.name for q in candidates]
            if self._last is None or self._last not in names:
                pick = candidates[0]
            else:
                pick = candidates[(names.index(self._last) + 1) % len(candidates)]
            self._last = pick.name
            return pick


class SmoothWeightedRoundRobinSelector:
    """Smooth WRR (nginx algorithm): current[i] += weight[i]; pick max;
    current[pick] -= total. Weight = pending task count, floored at 1 so a
    queue of zero-task units still drains."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._wrr = SmoothWRR()

    def next(self, queues: List[Queue]) -> Optional[Queue]:
        candidates = {q.name: q for q in queues if len(q) > 0}
        if not candidates:
            return None
        with self._lock:
            weights = {name: float(max(q.total_tasks(), 1))
                       for name, q in candidates.items()}
            return candidates[self._wrr.pick(weights)]
