"""Queue-selection policies: round-robin and smooth weighted round-robin.

Analog of /root/reference/pkg/coordinator/core/policy.go — RoundRobin (:31-76)
and WeightedRoundRobin (:80-230, the classic nginx gcd/maxWeight scan). Two
deliberate upgrades over the reference:

* WRR is actually wired in as the default (the reference built it but left
  plain RR in the ctor — coordinator.go:62, SURVEY §2.7 note);
* the weighted variant is *smooth* WRR (the reference's own TODO at
  policy.go:232): each pick adds weight to a running current-weight and picks
  the max, so a {5,1,1} weighting yields a-b-a-a-c-a-a instead of bursts.

Weight = total pending task count in the queue (calculateQueueWeight,
policy.go:224-230), recomputed every pick so weights track queue churn.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Protocol

from tpu_on_k8s.coordinator.queue import Queue


class QueueSelector(Protocol):
    def next(self, queues: List[Queue]) -> Optional[Queue]: ...


class RoundRobinSelector:
    """Plain RR over queue names (policy.go:31-76)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last: Optional[str] = None

    def next(self, queues: List[Queue]) -> Optional[Queue]:
        candidates = [q for q in queues if len(q) > 0]
        if not candidates:
            return None
        candidates.sort(key=lambda q: q.name)
        with self._lock:
            names = [q.name for q in candidates]
            if self._last is None or self._last not in names:
                pick = candidates[0]
            else:
                pick = candidates[(names.index(self._last) + 1) % len(candidates)]
            self._last = pick.name
            return pick


class SmoothWeightedRoundRobinSelector:
    """Smooth WRR (nginx algorithm): current[i] += weight[i]; pick max;
    current[pick] -= total. Weight = pending task count, floored at 1 so a
    queue of zero-task units still drains."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._current: Dict[str, float] = {}

    def next(self, queues: List[Queue]) -> Optional[Queue]:
        candidates = [q for q in queues if len(q) > 0]
        if not candidates:
            return None
        candidates.sort(key=lambda q: q.name)
        with self._lock:
            weights = {q.name: max(q.total_tasks(), 1) for q in candidates}
            total = sum(weights.values())
            # Drop state for vanished queues so their debt doesn't linger.
            self._current = {n: v for n, v in self._current.items() if n in weights}
            best: Optional[Queue] = None
            for q in candidates:
                cur = self._current.get(q.name, 0.0) + weights[q.name]
                self._current[q.name] = cur
                if best is None or cur > self._current[best.name]:
                    best = q
            self._current[best.name] -= total
            return best
