"""Coordinator core: tenant queues + the scheduling cycle.

Analog of /root/reference/pkg/coordinator/core/coordinator.go. A job entering
the cluster is **held** in a tenant queue (the watch path enqueues here rather
than into the reconciler workqueue — eventhandler.go:38-64); every scheduling
period one cycle runs: pick a queue via the selector (smooth WRR by default —
wired in, unlike the reference's plain-RR ctor at coordinator.go:62), scan its
snapshot through pre-filter/filter plugins (isQueueUnitAcceptable :389-430),
score the acceptable units (:434-452), pick the max with reservoir tie-break
(:456-476), run pre-dequeue plugins, then hand the job to its reconciler's
workqueue (Dequeue → Owner.Add, :226-248) and mark the status transition
Queuing→Dequeued (queueStateMarker :98-113).

The coordinator↔controller handshake race the reference has (SetQueueUnitOwner
skip-if-nil, SURVEY §7 hard parts) is designed out: the owner controller is a
required argument of ``enqueue_or_update``.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from tpu_on_k8s.api.types import JobConditionType, TPUJob
from tpu_on_k8s.client.cluster import InMemoryCluster, NotFoundError
from tpu_on_k8s.coordinator.plugins import PluginConfig
from tpu_on_k8s.coordinator.policy import (
    QueueSelector,
    SmoothWeightedRoundRobinSelector,
)
from tpu_on_k8s.coordinator.queue import Queue
from tpu_on_k8s.coordinator.types import Code, QueueUnit, Status
from tpu_on_k8s.metrics import JobMetrics
from tpu_on_k8s.utils import conditions
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("coordinator")

DEFAULT_SCHEDULING_PERIOD_SECONDS = 0.1  # plugins/registry.go:27


class Coordinator:
    def __init__(
        self,
        cluster: InMemoryCluster,
        plugins: Optional[PluginConfig] = None,
        selector: Optional[QueueSelector] = None,
        metrics: Optional[JobMetrics] = None,
        period_seconds: float = DEFAULT_SCHEDULING_PERIOD_SECONDS,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.cluster = cluster
        self.plugins = plugins or PluginConfig.default(cluster)
        self.selector = selector or SmoothWeightedRoundRobinSelector()
        self.metrics = metrics or JobMetrics()
        self.period = period_seconds
        self._rng = rng or random.Random()
        self._lock = threading.RLock()
        self._queues: Dict[str, Queue] = {}
        self._uid_to_tenant: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._phase_sweep_countdown = 0    # 0 ⇒ next cycle sweeps

    # ------------------------------------------------------------------- intake
    def enqueue_or_update(self, job: TPUJob, owner) -> None:
        """EnqueueOrUpdate (coordinator.go:195-233): place/update the job's
        queue unit and mark it Queuing. ``owner`` is the reconciler Controller
        whose workqueue receives the request on dequeue — explicit, closing the
        reference's SetQueueUnitOwner race."""
        unit = QueueUnit.from_job(job, owner=owner)
        unit.tenant = self.plugins.tenant.tenant_name(unit) if self.plugins.tenant \
            else job.metadata.namespace
        with self._lock:
            queue = self._queues.setdefault(unit.tenant, Queue(unit.tenant))
            stale_tenant = self._uid_to_tenant.get(unit.uid)
            if stale_tenant is not None and stale_tenant != unit.tenant:
                old = self._queues.get(stale_tenant)
                if old is not None:
                    old.remove(unit.uid)
            queue.add_or_update(unit)
            self._uid_to_tenant[unit.uid] = unit.tenant
        self._mark_queuing(job, unit.tenant)
        self._update_depth_gauges()

    def dequeue(self, job: TPUJob, *, reason: str = "") -> None:
        """Remove without scheduling (job deleted / no longer coordinated)."""
        self._remove(job.metadata.uid)
        self._release_reservations(job.metadata.uid)
        self._update_depth_gauges()

    def is_queuing(self, uid: str) -> bool:
        with self._lock:
            tenant = self._uid_to_tenant.get(uid)
            return tenant is not None and uid in self._queues.get(tenant, Queue(""))

    def queued_count(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def _remove(self, uid: str) -> Optional[QueueUnit]:
        with self._lock:
            tenant = self._uid_to_tenant.pop(uid, None)
            if tenant is None:
                return None
            queue = self._queues.get(tenant)
            if queue is None:
                return None
            unit = queue.remove(uid)
            if len(queue) == 0:
                del self._queues[tenant]
            return unit

    def _release_reservations(self, uid: str) -> None:
        for plugin in (self.plugins.pre_dequeues or []):
            release = getattr(plugin, "release", None)
            if release is not None:
                release(uid)

    def observe_job_left_queued_state(self, job: TPUJob) -> None:
        """Reservation cleanup hook: once a dequeued job is Running/finished its
        usage is real (visible to quota status), so drop the assumed quota
        (quota.go:256-277)."""
        if not conditions.needs_coordinator_enqueue(job.status):
            self._release_reservations(job.metadata.uid)

    # ------------------------------------------------------------------ cycle
    #: scheduling cycles between job-phase gauge sweeps (~5 s at the
    #: 100 ms loop period) — the sweep LISTs every TPUJob
    PHASE_GAUGE_SWEEP_CYCLES = 50

    def schedule_once(self) -> Optional[str]:
        """One scheduling cycle (coordinator.go:310-374). Returns the dequeued
        job key, or None if nothing was schedulable."""
        self._maybe_sweep_phase_gauges()
        with self._lock:
            queues = list(self._queues.values())
        queue = self.selector.next(queues)
        if queue is None:
            return None

        acceptable: List[QueueUnit] = []
        for unit in queue.snapshot():
            status = self._acceptable(unit)
            if status.code == Code.ERROR:
                self.cluster.record_event(
                    unit.job, "Warning", "CoordinateFailed", "; ".join(status.reasons))
                continue
            if not status.ok:
                continue
            acceptable.append(unit)
        if not acceptable:
            return None

        chosen = self._select_max_score(acceptable)
        for plugin in (self.plugins.pre_dequeues or []):
            if not plugin.pre_dequeue(chosen).ok:
                return None
        return self._dequeue_to_owner(chosen)

    def _acceptable(self, unit: QueueUnit) -> Status:
        """isQueueUnitAcceptable (coordinator.go:389-430)."""
        if self.cluster.try_get(
                TPUJob, unit.job.metadata.namespace, unit.job.metadata.name) is None:
            # Stale unit: job vanished without a delete event reaching us.
            self._remove(unit.uid)
            return Status.skip("job no longer exists")
        for plugin in (self.plugins.pre_filters or []):
            status = plugin.pre_filter(unit)
            if not status.ok:
                return status
        for plugin in (self.plugins.filters or []):
            status = plugin.filter(unit)
            if not status.ok:
                return status
        return Status.success()

    def _select_max_score(self, units: List[QueueUnit]) -> QueueUnit:
        """Max score with reservoir tie-break (selectQueueUnit :456-476)."""
        best: List[QueueUnit] = []
        best_score = float("-inf")
        for unit in units:
            score = sum(p.score(unit) for p in (self.plugins.scorers or []))
            if score > best_score:
                best, best_score = [unit], score
            elif score == best_score:
                best.append(unit)
        return best[0] if len(best) == 1 else self._rng.choice(best)

    def _dequeue_to_owner(self, unit: QueueUnit) -> Optional[str]:
        """Dequeue (coordinator.go:226-248): push into the reconciler workqueue
        and mark the Queuing→Dequeued status transition."""
        self._remove(unit.uid)
        job = self.cluster.try_get(
            TPUJob, unit.job.metadata.namespace, unit.job.metadata.name)
        if job is not None:
            self._mark_dequeued(job)
        if unit.owner is not None:
            unit.owner.enqueue(unit.job.metadata.namespace, unit.job.metadata.name)
        self._update_depth_gauges()
        return unit.key

    def drain(self, max_cycles: int = 10_000) -> int:
        """Run cycles until a full queue rotation yields nothing schedulable
        (tests / local driver). Returns dequeue count."""
        n = 0
        idle = 0
        for _ in range(max_cycles):
            with self._lock:
                n_queues = len(self._queues)
            if n_queues == 0:
                return n
            if self.schedule_once() is None:
                idle += 1
                # One idle cycle is not proof of quiescence under WRR rotation.
                if idle > n_queues:
                    return n
            else:
                idle = 0
                n += 1
        return n

    # ------------------------------------------------------------- status marks
    def _mark_queuing(self, job: TPUJob, tenant: str) -> None:
        """queueStateMarker (coordinator.go:98-113). ``tenant`` is the
        placement captured under the queue lock by the caller — the
        mutate closure must not re-read ``_uid_to_tenant`` lock-free
        (the schedule thread's ``_remove`` pops it concurrently, and a
        conflict retry would re-read mid-removal)."""
        def mutate(j: TPUJob) -> None:
            conditions.update_job_conditions(
                j.status, JobConditionType.QUEUING, "JobEnqueued",
                f"job enqueued in tenant queue {tenant}")
        self._write_if_changed(job, mutate)

    def _mark_dequeued(self, job: TPUJob) -> None:
        def mutate(j: TPUJob) -> None:
            conditions.update_job_conditions(
                j.status, JobConditionType.QUEUING, "JobDequeued",
                "job dequeued by coordinator", cond_status="False")
        self._write_if_changed(job, mutate)

    def _write_if_changed(self, job: TPUJob, mutate: Callable[[TPUJob], None]) -> None:
        """No-op writes are suppressed: every MODIFIED event re-enters the
        watch path, so unconditional writes would livelock enqueue."""
        try:
            current = self.cluster.get(TPUJob, job.metadata.namespace, job.metadata.name)
        except NotFoundError:
            return
        before = [(c.type, c.status, c.reason) for c in current.status.conditions]
        mutate(current)
        after = [(c.type, c.status, c.reason) for c in current.status.conditions]
        if before == after:
            return
        try:
            self.cluster.update_with_retry(
                TPUJob, job.metadata.namespace, job.metadata.name, mutate,
                subresource="status")
        except NotFoundError:
            pass

    def _update_depth_gauges(self) -> None:
        with self._lock:
            for name, queue in self._queues.items():
                self.metrics.set_gauge("queue_pending", float(len(queue)), label=name)

    def _update_phase_gauges(self) -> None:
        """Cluster-wide job-phase gauges (reference metrics.go:33-124
        keeps running/pending next to the queue depths): unfinished jobs
        split by the Running condition. A full LIST — O(jobs) against
        the API server in CRR mode — so it runs on the slow sweep
        cadence below, never per enqueue/dequeue."""
        running = pending = 0
        for job in self.cluster.list(TPUJob):
            if conditions.is_finished(job.status):
                continue
            if conditions.is_running(job.status):
                running += 1
            else:
                pending += 1
        self.metrics.set_gauge("running", float(running))
        self.metrics.set_gauge("pending", float(pending))

    def _maybe_sweep_phase_gauges(self) -> None:
        """Every PHASE_GAUGE_SWEEP_CYCLES scheduling cycles (~5 s at the
        100 ms loop period); counter-based so no wall clock enters the
        scheduling path. The first cycle sweeps immediately. A failed
        LIST (an API-server blip in CRR mode) must not abort the
        scheduling cycle — it is counted, and the sweep retries next
        cycle instead of waiting out a full period."""
        if self._phase_sweep_countdown > 0:
            self._phase_sweep_countdown -= 1
            return
        try:
            self._update_phase_gauges()
        except Exception:
            self.metrics.error()
            _log.warning("job-phase gauge sweep failed; retrying next "
                         "cycle", exc_info=True)
            return               # countdown stays 0 → next cycle retries
        self._phase_sweep_countdown = self.PHASE_GAUGE_SWEEP_CYCLES - 1

    # --------------------------------------------------------------- run loop
    def run(self) -> None:
        """100ms schedule loop (coordinator.go:305-307), background thread."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.schedule_once()
                except Exception:  # cycle errors must not kill the loop
                    _log.exception("coordinator schedule cycle failed")
                    if self.metrics is not None:
                        self.metrics.error()
                self._stop.wait(self.period)

        # start before publishing: stop() must never observe (and join) a
        # created-but-unstarted thread
        t = threading.Thread(target=loop, daemon=True, name="coordinator")
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)
