"""Coordinator data types and plugin contracts.

Analog of /root/reference/pkg/coordinator/{types.go,interface.go}: the
``QueueUnit`` a tenant queue holds (types.go:46-79), scheduling-cycle status
codes (types.go:89-176), and the five plugin extension points
(interface.go:55-82). Plugins are plain objects implementing the protocols —
no reflection-based registry wiring (the reference's coordinator.go:116-162
reflection dance is replaced by an explicit PluginConfig).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional, Protocol, runtime_checkable

from tpu_on_k8s.api.types import SchedulingPolicy, TPUJob
from tpu_on_k8s.utils import resources as resmath


class Code(enum.IntEnum):
    """Cycle status codes (reference types.go:89-176)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    WAIT = 3
    SKIP = 4


@dataclass
class Status:
    code: Code = Code.SUCCESS
    reasons: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.code == Code.SUCCESS

    @classmethod
    def success(cls) -> "Status":
        return cls(Code.SUCCESS)

    @classmethod
    def wait(cls, *reasons: str) -> "Status":
        return cls(Code.WAIT, list(reasons))

    @classmethod
    def error(cls, *reasons: str) -> "Status":
        return cls(Code.ERROR, list(reasons))

    @classmethod
    def unschedulable(cls, *reasons: str) -> "Status":
        return cls(Code.UNSCHEDULABLE, list(reasons))

    @classmethod
    def skip(cls, *reasons: str) -> "Status":
        return cls(Code.SKIP, list(reasons))


@dataclass
class QueueUnit:
    """One queued job (reference types.go:46-79). ``owner`` is the reconciler
    controller whose workqueue receives the request on dequeue
    (core/coordinator.go:226-248 Owner.Add)."""

    tenant: str = ""
    job: Optional[TPUJob] = None
    priority: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None
    resources: Dict[str, float] = field(default_factory=dict)
    spot_resources: Dict[str, float] = field(default_factory=dict)
    owner: object = None  # Controller with .enqueue(ns, name)

    @property
    def uid(self) -> str:
        return self.job.metadata.uid

    @property
    def key(self) -> str:
        return f"{self.job.metadata.namespace}/{self.job.metadata.name}"

    @classmethod
    def from_job(cls, job: TPUJob, owner=None, tenant: str = "") -> "QueueUnit":
        policy = job.spec.run_policy.scheduling_policy
        return cls(
            tenant=tenant,
            job=job,
            priority=policy.priority if policy else None,
            scheduling_policy=policy,
            resources=resmath.job_requests(job, include_spot=False),
            spot_resources=resmath.job_spot_requests(job),
            owner=owner,
        )

    def total_tasks(self) -> int:
        return sum(t.num_tasks for t in self.job.spec.tasks.values())


@runtime_checkable
class TenantPlugin(Protocol):
    """Maps a queue unit to its tenant queue name (interface.go TenantPlugin)."""

    def tenant_name(self, unit: QueueUnit) -> str: ...


@runtime_checkable
class PreFilterPlugin(Protocol):
    def pre_filter(self, unit: QueueUnit) -> Status: ...


@runtime_checkable
class FilterPlugin(Protocol):
    def filter(self, unit: QueueUnit) -> Status: ...


@runtime_checkable
class ScorePlugin(Protocol):
    def score(self, unit: QueueUnit) -> float: ...


@runtime_checkable
class PreDequeuePlugin(Protocol):
    def pre_dequeue(self, unit: QueueUnit) -> Status: ...
