"""Tenant queue: UID-keyed unit container with snapshot iteration.

Analog of /root/reference/pkg/coordinator/core/queue.go:28-121 — deliberately
NOT FIFO: the scheduling cycle scans a point-in-time snapshot and picks by
plugin score, so insertion order carries no meaning.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from tpu_on_k8s.coordinator.types import QueueUnit


class Queue:
    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.RLock()
        self._units: Dict[str, QueueUnit] = {}  # uid → unit

    def add_or_update(self, unit: QueueUnit) -> None:
        with self._lock:
            self._units[unit.uid] = unit

    def remove(self, uid: str) -> Optional[QueueUnit]:
        with self._lock:
            return self._units.pop(uid, None)

    def get(self, uid: str) -> Optional[QueueUnit]:
        with self._lock:
            return self._units.get(uid)

    def __contains__(self, uid: str) -> bool:
        with self._lock:
            return uid in self._units

    def __len__(self) -> int:
        with self._lock:
            return len(self._units)

    def snapshot(self) -> List[QueueUnit]:
        """Point-in-time iteration copy (reference queue.go:97-101 iterator)."""
        with self._lock:
            return list(self._units.values())

    def total_tasks(self) -> int:
        """Pending task count — the WRR queue weight
        (reference core/policy.go:224-230 calculateQueueWeight)."""
        with self._lock:
            return sum(u.total_tasks() for u in self._units.values())
