"""SLO-driven serving autoscaling: fleet load → ``InferenceService.replicas``.

Training replicas already scale elastically
(`controller/autoscaler.ElasticAutoscaler`); this package closes the same
loop for the serving plane:

* `signals` — windowed aggregation of per-replica gateway/fleet metrics
  (TTFT p95, queue-wait p95, queue depth, tokens-in-flight per slot)
  into a ``FleetObservation``, with an explicit staleness bit so a dead
  scrape is "no data", never "zero load";
* `policy`  — ``Recommender``: a deterministic target-tracking policy
  (SLO targets + utilization band) producing **slice-legal** replica
  targets via `gang/topology.next_legal_host_count`, with hysteresis,
  separate up/down cooldowns, flap damping, bounded step size, and a
  ``min_warm`` warm floor (slice spin-up is minutes — reactive-only
  scaling misses bursts);
* execution lives in `controller/fleetautoscaler.FleetAutoscaler`, the
  second control loop over the ``InferenceService`` CRD: it patches
  ``spec.replicas`` and lets the reconciler's surge/drain machinery
  (and, in-process, ``ServingFleet.scale_to``) do the rest.
"""
from tpu_on_k8s.autoscale.policy import (
    ACTION_DOWN,
    ACTION_HOLD,
    ACTION_UP,
    Decision,
    Recommender,
)
from tpu_on_k8s.autoscale.signals import (
    NO_DATA,
    FleetObservation,
    FleetSample,
    FleetScraper,
    SignalAggregator,
    dead_sample,
    line_watermark,
    sample_from_line,
)

__all__ = [
    "ACTION_DOWN",
    "ACTION_HOLD",
    "ACTION_UP",
    "Decision",
    "FleetObservation",
    "FleetSample",
    "FleetScraper",
    "NO_DATA",
    "Recommender",
    "SignalAggregator",
    "dead_sample",
    "line_watermark",
    "sample_from_line",
]
