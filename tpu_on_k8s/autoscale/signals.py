"""Serving-load signals: windowed aggregation with an explicit staleness bit.

The policy layer (`autoscale/policy.py`) wants ONE coherent picture of
fleet load — TTFT p95, queue-wait p95, queue depth, tokens-in-flight per
slot — not a firehose of per-replica histograms. This module produces
that picture:

* ``FleetSample`` — one scrape: the *new* latency observations since the
  previous scrape plus instantaneous load gauges. ``ok=False`` is a
  **dead scrape** (metrics endpoint down, log tail empty): it carries no
  data and must never read as "zero load".
* ``FleetScraper`` — delta reader over a live ``ServingFleet``: tracks
  per-replica histogram read positions so each scrape sees only fresh
  observations (the mirror deques are cumulative and bounded).
* ``sample_from_line`` — the out-of-process twin: parse one extended
  ``[elastic-metrics]`` observation line (what
  ``ServingFleet.observation_line()`` prints and the controller tails
  from replica pod logs) into the same ``FleetSample`` shape.
* ``SignalAggregator`` — a bounded window of scrapes folded into a
  ``FleetObservation``. Staleness is explicit: ``stale_after``
  consecutive dead scrapes (or an all-empty window) marks the
  observation stale, and the policy HOLDS on stale — a dead scrape is
  "no data", not "the fleet is idle, scale to min".

Everything here is stdlib-only and deterministic: percentiles are
nearest-rank over sorted windows, sequence numbers are scrape counts,
no wall-clock enters the aggregation.
"""
from __future__ import annotations

import dataclasses
import math
import re
from collections import deque
from typing import Deque, Dict, Optional, Tuple

#: the no-data sentinel in observation lines: ``latency=nan`` means "no
#: TTFT/queue sample exists yet" — parsers must map it to None, never 0.0
NO_DATA = float("nan")

_ACTIVE_REPLICA_STATES = ("starting", "ready", "draining")

#: the observation-line vocabulary — this module is the single home
#: (stdlib-only); `controller/autoscaler.py` imports it from here
KV_RE = re.compile(r"(\w+)=([^\s]+)")
METRICS_TAG = "[elastic-metrics]"


@dataclasses.dataclass(frozen=True)
class FleetSample:
    """One scrape of the fleet. ``ttft`` / ``queue_wait`` are the NEW
    latency observations (seconds) since the previous scrape; the rest
    are instantaneous gauges. ``ok=False`` marks a dead scrape — every
    payload field is meaningless and the aggregator counts it toward
    staleness instead of folding it in."""

    seq: int
    ttft: Tuple[float, ...] = ()
    queue_wait: Tuple[float, ...] = ()
    tpot: Tuple[float, ...] = ()
    #: model swap-in latencies (seconds) since the previous scrape —
    #: multi-model replicas (`serve/modelpool.py`) mirror their pool's
    #: ``swap_seconds`` histogram into the replica metrics; swap-in is
    #: the pool's cold-start cost and a first-class scaling signal
    #: beside TTFT. Single-model fleets never populate it.
    swap: Tuple[float, ...] = ()
    queue_depth: int = 0
    inflight_tokens: int = 0
    slots: int = 0
    ready_replicas: int = 0
    ok: bool = True
    #: trace ids (`obs/trace.py` counter ids) of the newest retained
    #: TTFT exemplars at scrape time — the join key from this scrape's
    #: latency picture back to the request span trees that produced it
    #: (the decision ledger records these on every decision). Empty when
    #: tracing is off; the log-scrape plane leaves it empty too.
    exemplars: Tuple[int, ...] = ()


def dead_sample(seq: int) -> FleetSample:
    """A scrape that failed: no data, not zero load."""
    return FleetSample(seq=seq, ok=False)


@dataclasses.dataclass(frozen=True)
class FleetObservation:
    """The window folded down: what the policy decides on. Latency
    percentiles are ``None`` (never 0.0) when the window holds no
    sample of that kind; ``stale`` means the window itself can't be
    trusted and the policy must hold last-known-good."""

    seq: int
    ttft_p95: Optional[float]
    queue_wait_p95: Optional[float]
    queue_depth: int
    inflight_tokens: int
    slots: int
    ready_replicas: int
    samples: int          # latency observations backing the percentiles
    stale: bool
    #: inter-token latency p95 (seconds/token) — the decode pool's SLO
    #: signal in disaggregated serving; defaulted so pre-disagg
    #: constructors (and their tests) stay source-compatible
    tpot_p95: Optional[float] = None
    #: model swap-in latency p95 (seconds) — the multi-model cold-start
    #: signal (`policy.target_swap_s`); defaulted for the same
    #: source-compatibility reason as ``tpot_p95``
    swap_p95: Optional[float] = None

    @property
    def tokens_per_slot(self) -> Optional[float]:
        """Utilization: outstanding token cost per engine slot (the
        band `policy.util_high`/`util_low` compares against). None when
        slot capacity is unknown (e.g. a stale window)."""
        if self.slots <= 0:
            return None
        return self.inflight_tokens / self.slots


def percentile(values, q: float) -> Optional[float]:
    """Nearest-rank percentile; None on an empty set (no data is never
    a number). The ONE percentile definition every emitter and consumer
    of these signals shares — two formulas would make the log-scrape
    and in-process planes disagree on identical data."""
    vals = sorted(values)
    if not vals:
        return None
    idx = min(len(vals) - 1, max(0, math.ceil(q * len(vals)) - 1))
    return vals[idx]


class FleetScraper:
    """Delta reader over a live ``ServingFleet`` (duck-typed: anything
    with a ``replicas`` dict of objects carrying ``metrics`` /
    ``engine`` / ``outstanding`` / ``routable`` / ``state``). Each
    scrape returns only the latency observations appended since the
    previous one — the mirror deques are cumulative, and re-counting
    old samples would let one ancient breach scale the fleet forever."""

    def __init__(self) -> None:
        self._seen: Dict[Tuple[str, str], int] = {}
        self._seq = 0

    def scrape(self, fleet, seq: Optional[int] = None) -> FleetSample:
        """``seq`` lets the caller own the scrape numbering (the
        controller shares one counter across live scrapes AND dead
        ones, so outage ticks never make the sequence regress);
        standalone callers omit it and get the internal counter."""
        if seq is None:
            self._seq += 1
            seq = self._seq
        else:
            self._seq = seq
        ttft = []
        qwait = []
        tpot = []
        swap = []
        exemplars = []
        slots = 0
        inflight = 0
        ready = 0
        # bind once: a DisaggPool's ``replicas`` property takes the
        # fleet lock and rebuilds a filtered dict per access — one
        # snapshot here is one lock acquisition instead of N+1
        replicas = fleet.replicas
        for name in sorted(replicas):
            rep = replicas[name]
            state = getattr(rep.state, "value", str(rep.state))
            if state not in _ACTIVE_REPLICA_STATES:
                continue
            if rep.engine is not None:
                slots += getattr(rep.engine, "n_slots", 0)
            inflight += rep.outstanding
            ready += bool(rep.routable)
            if rep.metrics is None:
                continue
            for key, out in (("time_to_first_token_seconds", ttft),
                             ("queue_wait_seconds", qwait),
                             ("time_per_output_token_seconds", tpot),
                             # multi-model replicas mirror their pool's
                             # swap-in latency here; the mirror is a
                             # defaultdict, so plain fleets read empty
                             ("swap_seconds", swap)):
                # snapshot under the mirror lock: the gateway appends
                # from the driver thread while this scrape runs in the
                # autoscaler's. Position by the monotone observation
                # count, NOT len(): the mirror deque is bounded, and
                # len() freezes once it saturates — a length-based
                # cursor would go permanently blind on a fleet that has
                # served more than MIRROR_CAP requests.
                with rep.metrics._lock:
                    vals = list(rep.metrics.histograms[key])
                    total = rep.metrics.histogram_counts.get(key, 0)
                mark = (name, key)
                n = self._seen.get(mark, 0)
                if total < n:
                    n = 0      # metrics instance was reset: restart
                new = total - n
                if new > 0:
                    # samples beyond the deque's capacity rotated away
                    # before this scrape — take what survives
                    out.extend(vals[-min(new, len(vals)):])
                self._seen[mark] = total
            # newest retained TTFT exemplar trace ids (≤2 per replica):
            # the decision ledger's span join key. Not delta-read — the
            # exemplar deque carries no monotone count; "the freshest
            # evidence at scrape time" is exactly what a decision cites.
            # (duck-typed like the rest of the scrape: a bare-histogram
            # metrics stub simply contributes none)
            mirror = getattr(rep.metrics, "exemplars", None)
            if mirror is not None:
                with rep.metrics._lock:
                    tail = list(mirror["time_to_first_token_seconds"])[-2:]
                exemplars.extend(int(tid) for _, tid in tail
                                 if isinstance(tid, int))
        return FleetSample(
            seq=seq, ttft=tuple(ttft), queue_wait=tuple(qwait),
            tpot=tuple(tpot), swap=tuple(swap),
            queue_depth=fleet.queue_depth, inflight_tokens=inflight,
            slots=slots, ready_replicas=ready,
            exemplars=tuple(exemplars))


def format_observation_line(sample: FleetSample, *, epoch: int,
                            batch: int) -> str:
    """Render a ``FleetSample`` as the extended ElasticAutoscaler
    observation line — the ONE emitter behind
    ``ServingFleet.observation_line`` and
    ``DisaggFleet.pool_observation_line``, and the inverse of
    `sample_from_line` (the format is load-bearing: the log-scraping
    autoscaler plane parses it, so a field added here reaches every
    fleet type at once). With no latency sample of any kind the
    ``latency`` field carries the ``nan`` sentinel — "no data", which
    every parser maps to None, never "infinitely fast"."""
    def p95(vals) -> float:
        v = percentile(vals, 0.95)
        return NO_DATA if v is None else v

    src = sample.ttft or sample.queue_wait
    return (f"{METRICS_TAG} epoch={epoch} batch={batch} "
            f"latency={p95(src):.6f} accuracy=0.0 "
            f"queue_wait={p95(sample.queue_wait):.6f} "
            f"queue_depth={sample.queue_depth} "
            f"inflight={sample.inflight_tokens} "
            f"slots={sample.slots} ready={sample.ready_replicas} "
            f"tpot={p95(sample.tpot):.6f} swap={p95(sample.swap):.6f}")


def sample_from_line(line: str, seq: int) -> Optional[FleetSample]:
    """Parse one extended observation line (the
    ``ServingFleet.observation_line()`` format) into a ``FleetSample``;
    None if the line isn't one. The ``latency`` / ``queue_wait`` values
    are window percentiles the emitter already computed, so they enter
    the sample as single observations; the ``nan`` sentinel (and any
    non-finite or negative value) contributes NO observation — the
    whole point of the sentinel is that "no data yet" must never fold
    in as "latency 0"."""
    if METRICS_TAG not in line:
        return None
    fields = dict(KV_RE.findall(line))
    if "latency" not in fields:
        return None

    def _lat(key: str) -> Tuple[float, ...]:
        try:
            v = float(fields[key])
        except (KeyError, ValueError):
            return ()
        return (v,) if math.isfinite(v) and v >= 0.0 else ()

    def _int(key: str) -> int:
        try:
            v = int(float(fields[key]))
        except (KeyError, ValueError, OverflowError):
            return 0   # OverflowError: int(float("9e999"))
        return max(v, 0)

    return FleetSample(
        seq=seq, ttft=_lat("latency"), queue_wait=_lat("queue_wait"),
        tpot=_lat("tpot"), swap=_lat("swap"),
        queue_depth=_int("queue_depth"), inflight_tokens=_int("inflight"),
        slots=_int("slots"), ready_replicas=_int("ready"))


def line_watermark(line: str) -> Optional[int]:
    """The ``batch=`` (fleet step) counter of an observation line — the
    monotone marker the log-tailing controller uses to take each line
    exactly once. None if the line isn't an observation."""
    if METRICS_TAG not in line:
        return None
    fields = dict(KV_RE.findall(line))
    try:
        return int(float(fields["batch"]))
    except (KeyError, ValueError, OverflowError):
        return None


class SignalAggregator:
    """A bounded window of scrapes → one ``FleetObservation``.

    ``window`` scrapes are aggregated (latency percentiles over their
    union; gauges from the newest live scrape). ``stale_after``
    consecutive dead scrapes mark the observation **stale** — the
    policy's cue to hold last-known-good. Dead scrapes never evict live
    data from the window (a one-tick outage must not blank the
    picture); they only advance the staleness streak.

    ``max_age_s`` adds TIME-based staleness on top of the count-based
    streak: samples are stamped with the ``now`` the caller passes to
    ``record``, and samples older than ``max_age_s`` stop contributing.
    Without it, a clock that jumps past the whole window (a wedged
    controller thread, a long GC pause, a virtual clock skipping ahead)
    leaves ancient samples masquerading as fresh — the burn-rate /
    policy layers would keep acting on a picture that is entirely
    history. A window that ages out completely is **stale**, never a
    frozen last-known-good. ``None`` (the default) disables aging —
    byte-for-byte the previous behavior."""

    def __init__(self, window: int = 4, stale_after: int = 3,
                 max_age_s: Optional[float] = None) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if stale_after < 1:
            raise ValueError(f"stale_after must be >= 1, got {stale_after}")
        if max_age_s is not None and max_age_s <= 0:
            raise ValueError(f"max_age_s must be > 0, got {max_age_s}")
        self.window = window
        self.stale_after = stale_after
        self.max_age_s = max_age_s
        # (sample, recorded-at) — the stamp is the caller's clock, None
        # when the caller never passes one (aging then can't apply)
        self._samples: Deque[Tuple[FleetSample, Optional[float]]] = deque(
            maxlen=window)
        self._dead_streak = 0
        self._seq = 0
        self._now: Optional[float] = None

    def record(self, sample: FleetSample,
               now: Optional[float] = None) -> FleetObservation:
        self._seq = sample.seq
        if now is not None:
            self._now = now
        if sample.ok:
            self._dead_streak = 0
            self._samples.append((sample, now))
        else:
            self._dead_streak += 1
        return self.observation()

    def _live_samples(self):
        if self.max_age_s is None or self._now is None:
            return [s for s, _ in self._samples]
        return [s for s, t in self._samples
                if t is None or self._now - t <= self.max_age_s]

    def observation(self) -> FleetObservation:
        live = self._live_samples()
        ttft = [v for s in live for v in s.ttft]
        qwait = [v for s in live for v in s.queue_wait]
        tpot = [v for s in live for v in s.tpot]
        swap = [v for s in live for v in s.swap]
        latest = live[-1] if live else None
        stale = self._dead_streak >= self.stale_after or latest is None
        return FleetObservation(
            seq=self._seq,
            ttft_p95=percentile(ttft, 0.95),
            queue_wait_p95=percentile(qwait, 0.95),
            tpot_p95=percentile(tpot, 0.95),
            swap_p95=percentile(swap, 0.95),
            queue_depth=latest.queue_depth if latest else 0,
            inflight_tokens=latest.inflight_tokens if latest else 0,
            slots=latest.slots if latest else 0,
            ready_replicas=latest.ready_replicas if latest else 0,
            samples=len(ttft) + len(qwait) + len(tpot) + len(swap),
            stale=stale)
