"""Deterministic target-tracking recommender: SLO signals → replica target.

The decision core of the serving autoscaler, deliberately free of I/O so
every decision is a pure function of ``(observation, current replicas,
clock)`` plus a handful of monotone stamps — the property that makes two
runs of the same seeded trace produce byte-identical decision logs
(`make autoscale-soak` enforces this).

Policy shape (knobs live on the CRD as
`api/inference_types.AutoscalePolicy`):

* **SLO targets** — scale up when TTFT p95 or queue-wait p95 breaches
  the target by more than the hysteresis margin; scale down only when
  every configured signal reads comfortably BELOW target (the dead band
  between the two thresholds absorbs noise).
* **Utilization band** — tokens-in-flight per engine slot above
  ``util_high`` scales up even before latency degrades (queueing theory:
  at high utilization, wait explodes); below ``util_low`` (with an empty
  queue) it is scale-down evidence.
* **Slice-legal steps** — TPU serving replicas occupy whole slices, and
  host counts come in topology quanta: steps land on
  `gang/topology.next_legal_host_count` values, never free-form N±1
  (on v5e those coincide at small counts; on 3D-torus parts they do not).
* **Tempo** — separate scale-up/scale-down cooldowns (up is cheap to
  regret, down risks an SLO breach), flap damping (a direction reversal
  needs ``flap_guard_s`` since the opposite move), and a bounded step
  size scaled by breach severity.
* **Warm floor** — ``min_warm`` pre-provisions capacity for burst
  absorption: slice spin-up is minutes, not seconds, so a purely
  reactive policy structurally misses the front of every burst (the
  elastic-allocation argument in PAPERS.md). The floor overrides load
  evidence and is exempt from cooldowns — it is configuration, not
  reaction.
* **Outage** — a stale observation (see `autoscale/signals.py`) holds
  last-known-good. No data is never "no load".
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from tpu_on_k8s.autoscale.signals import FleetObservation
from tpu_on_k8s.controller.loopkernel import (
    CooldownGate,
    format_decision_line,
)
from tpu_on_k8s.gang import topology

ACTION_UP = "up"
ACTION_DOWN = "down"
ACTION_HOLD = "hold"


@dataclasses.dataclass(frozen=True)
class Decision:
    """One recommendation. ``line()`` is the stable decision-log form
    (the shared `controller/loopkernel` serializer — byte-identical to
    the historical format): only observation-derived values
    (deterministic under an injected clock) — no wall time, no object
    ids."""

    seq: int
    action: str
    current: int
    target: int
    reason: str

    def line(self) -> str:
        return format_decision_line(self.seq, self.action, self.current,
                                    self.target, self.reason)


def _fmt(v: Optional[float]) -> str:
    return "none" if v is None else f"{v:.6f}"


class Recommender:
    """Target-tracking policy evaluation. ``decide()`` is pure (no state
    mutated); the caller ``commit()``s a decision only after executing
    it, so a failed patch never burns a cooldown window."""

    def __init__(self, policy, *, accelerator: str = "") -> None:
        # ``policy`` is an api.inference_types.AutoscalePolicy (duck-typed
        # to keep this module importable without the api layer in tests)
        self.policy = policy.normalized() if hasattr(policy, "normalized") \
            else policy
        self.accelerator = accelerator if getattr(
            self.policy, "slice_legal", True) else ""
        # tempo state lives in the shared loop-kernel gate: separate
        # up/down cooldowns + flap damping, stamped only on commit
        self.gate = CooldownGate(
            up_cooldown_s=getattr(self.policy, "scale_up_cooldown_s", 0.0),
            down_cooldown_s=getattr(self.policy, "scale_down_cooldown_s",
                                    0.0),
            flap_guard_s=getattr(self.policy, "flap_guard_s", 0.0))

    # ------------------------------------------------------------ legality
    def _step_up(self, cur: int) -> Optional[int]:
        if self.accelerator:
            return topology.next_legal_host_count(self.accelerator, cur)
        return cur + 1

    def _step_down(self, cur: int) -> Optional[int]:
        if self.accelerator:
            return topology.next_legal_host_count(self.accelerator, cur,
                                                  direction=-1)
        return cur - 1 if cur > 1 else None

    def legalize_up(self, desired: int) -> int:
        """Smallest legal count >= desired (identity without an
        accelerator)."""
        if self.accelerator:
            return topology.snap_host_count(self.accelerator, desired)
        return desired

    def legalize_down(self, desired: int) -> Optional[int]:
        """Largest legal count <= desired (identity without an
        accelerator; None when every legal count exceeds it)."""
        if not self.accelerator:
            return desired
        if desired in topology.legal_host_counts(self.accelerator):
            return desired
        return topology.next_legal_host_count(self.accelerator, desired,
                                              direction=-1)

    # ------------------------------------------------------------ decision
    def decide(self, obs: FleetObservation, cur: int, now: float, *,
               urgent: bool = False) -> Decision:
        """``urgent`` is the SLO engine's severity hint (an error-budget
        objective is PAGING — `tpu_on_k8s/obs/slo.py`): a scale-up that
        would otherwise sit out the up-cooldown executes immediately,
        marked ``slo_page`` in the reason. Nothing else changes — the
        flap guard, max bound, and slice legality all still apply, and
        the default (False) is byte-for-byte the pre-SLO decision path."""
        p = self.policy
        floor = max(p.min_replicas, p.min_warm)

        # warm floor first: pre-provisioned burst capacity is config, not
        # load reaction — no cooldown, no signal needed, stale or not.
        # The target stays slice-legal even when floor/max_replicas are
        # not themselves legal quanta: snap the floor up, and fall back
        # to the largest legal count under max if that overshoots.
        if cur < floor:
            target = self.legalize_up(floor)
            if target > p.max_replicas:
                target = self.legalize_down(p.max_replicas)
            if target is not None and target > cur:
                return Decision(obs.seq, ACTION_UP, cur, target,
                                f"warm_floor {floor}")

        if obs.stale:
            return Decision(obs.seq, ACTION_HOLD, cur, cur,
                            "stale_signal holding_last_known_good")

        up = self._up_reasons(obs)
        if up:
            return self._scale_up(obs, cur, now, up, urgent=urgent)
        if self._down_ok(obs, cur):
            return self._scale_down(obs, cur, now)
        return Decision(obs.seq, ACTION_HOLD, cur, cur, "steady")

    def commit(self, decision: Decision, now: float) -> None:
        """Record a *executed* scale (cooldown/flap stamps). Warm-floor
        bumps are exempt — they must not delay the first load-driven
        scale-up."""
        if decision.reason.startswith("warm_floor"):
            return
        self.gate.commit(decision.action, now)

    # ----------------------------------------------------------- internals
    def _up_reasons(self, obs: FleetObservation) -> List[str]:
        p = self.policy
        h = 1.0 + p.hysteresis
        reasons: List[str] = []
        if p.target_ttft_s > 0 and obs.ttft_p95 is not None \
                and obs.ttft_p95 > p.target_ttft_s * h:
            reasons.append(f"ttft_p95={_fmt(obs.ttft_p95)}"
                           f">slo={_fmt(p.target_ttft_s)}")
        if p.target_queue_wait_s > 0 and obs.queue_wait_p95 is not None \
                and obs.queue_wait_p95 > p.target_queue_wait_s * h:
            reasons.append(f"queue_wait_p95={_fmt(obs.queue_wait_p95)}"
                           f">slo={_fmt(p.target_queue_wait_s)}")
        tpot_slo = getattr(p, "target_tpot_s", 0.0)
        if tpot_slo > 0 and obs.tpot_p95 is not None \
                and obs.tpot_p95 > tpot_slo * h:
            reasons.append(f"tpot_p95={_fmt(obs.tpot_p95)}"
                           f">slo={_fmt(tpot_slo)}")
        # model swap-in latency: the multi-model cold-start signal — a
        # breach means models are churning through too little residency
        # and the fleet needs more replicas (duck-typed getattr like
        # tpot, so policy stubs without the knob keep working)
        swap_slo = getattr(p, "target_swap_s", 0.0)
        swap_p95 = getattr(obs, "swap_p95", None)
        if swap_slo > 0 and swap_p95 is not None \
                and swap_p95 > swap_slo * h:
            reasons.append(f"swap_p95={_fmt(swap_p95)}"
                           f">slo={_fmt(swap_slo)}")
        util = obs.tokens_per_slot
        if p.util_high > 0 and util is not None and util > p.util_high:
            reasons.append(f"tokens_per_slot={_fmt(util)}"
                           f">high={_fmt(p.util_high)}")
        return reasons

    def _severity(self, obs: FleetObservation) -> float:
        """Worst breach ratio across configured signals — how many
        bounded steps the scale-up takes (a 3x TTFT breach should not
        crawl up one quantum per cooldown window)."""
        p = self.policy
        worst = 1.0
        if p.target_ttft_s > 0 and obs.ttft_p95 is not None:
            worst = max(worst, obs.ttft_p95 / p.target_ttft_s)
        if p.target_queue_wait_s > 0 and obs.queue_wait_p95 is not None:
            worst = max(worst, obs.queue_wait_p95 / p.target_queue_wait_s)
        tpot_slo = getattr(p, "target_tpot_s", 0.0)
        if tpot_slo > 0 and obs.tpot_p95 is not None:
            worst = max(worst, obs.tpot_p95 / tpot_slo)
        swap_slo = getattr(p, "target_swap_s", 0.0)
        swap_p95 = getattr(obs, "swap_p95", None)
        if swap_slo > 0 and swap_p95 is not None:
            worst = max(worst, swap_p95 / swap_slo)
        util = obs.tokens_per_slot
        if p.util_high > 0 and util is not None:
            worst = max(worst, util / p.util_high)
        return worst

    def _scale_up(self, obs: FleetObservation, cur: int, now: float,
                  reasons: List[str], *, urgent: bool = False) -> Decision:
        p = self.policy
        reason = ",".join(reasons)
        if cur >= p.max_replicas:
            return Decision(obs.seq, ACTION_HOLD, cur, cur,
                            f"at_max {reason}")
        in_cooldown = self.gate.up_in_cooldown(now)
        if in_cooldown and not urgent:
            return Decision(obs.seq, ACTION_HOLD, cur, cur,
                            f"up_cooldown {reason}")
        if in_cooldown:
            # paged through the cooldown: the reason says so, so the
            # decision log attributes the early move to the budget burn
            reason = f"slo_page {reason}"
        if self.gate.flap_blocked(ACTION_UP, now):
            return Decision(obs.seq, ACTION_HOLD, cur, cur,
                            f"flap_damped {reason}")
        steps = min(p.max_step, max(1, int(self._severity(obs))))
        target = cur
        for _ in range(steps):
            nxt = self._step_up(target)
            if nxt is None or nxt > p.max_replicas:
                break
            target = nxt
        if target == cur:
            # the next legal quantum overshoots max_replicas: an
            # integer-mode policy would have stepped, a slice-legal one
            # is simply capped here
            return Decision(obs.seq, ACTION_HOLD, cur, cur,
                            f"at_max_legal {reason}")
        return Decision(obs.seq, ACTION_UP, cur, target, reason)

    def _down_ok(self, obs: FleetObservation, cur: int) -> bool:
        """Scale-down needs EVERY configured signal comfortably low.
        Missing latency data (no recent requests) counts as low only
        when the load gauges prove the fleet idle — absent data alone
        must never read as fast."""
        p = self.policy
        h = 1.0 - p.hysteresis
        tpot_slo = getattr(p, "target_tpot_s", 0.0)
        swap_slo = getattr(p, "target_swap_s", 0.0)
        idle = obs.queue_depth == 0 and obs.inflight_tokens == 0
        if not (p.target_ttft_s > 0 or p.target_queue_wait_s > 0
                or tpot_slo > 0 or swap_slo > 0 or p.util_low > 0):
            # no scale-down signal configured at all: a zero-signal
            # policy must hold, not ratchet a live fleet to min on
            # "queue happens to be empty"
            return False
        if obs.ready_replicas < cur:
            return False   # world still assembling — never shrink into it
        if obs.queue_depth > 0:
            return False
        if p.target_ttft_s > 0:
            if obs.ttft_p95 is None:
                if not idle:
                    return False
            elif obs.ttft_p95 >= p.target_ttft_s * h:
                return False
        if p.target_queue_wait_s > 0:
            if obs.queue_wait_p95 is None:
                if not idle:
                    return False
            elif obs.queue_wait_p95 >= p.target_queue_wait_s * h:
                return False
        if tpot_slo > 0:
            if obs.tpot_p95 is None:
                if not idle:
                    return False
            elif obs.tpot_p95 >= tpot_slo * h:
                return False
        if swap_slo > 0:
            # a breaching swap p95 blocks shrink; NO swap data does not
            # (an all-warm pool that never swaps is the goal state, not
            # missing evidence — unlike request latency, absence of
            # swaps under live traffic is itself a healthy signal)
            swap_p95 = getattr(obs, "swap_p95", None)
            if swap_p95 is not None and swap_p95 >= swap_slo * h:
                return False
        if p.util_low > 0:
            util = obs.tokens_per_slot
            if util is None or util >= p.util_low:
                return False
        return True

    def _scale_down(self, obs: FleetObservation, cur: int,
                    now: float) -> Decision:
        p = self.policy
        floor = max(p.min_replicas, p.min_warm)
        reason = (f"underutilized ttft_p95={_fmt(obs.ttft_p95)} "
                  f"tokens_per_slot={_fmt(obs.tokens_per_slot)}")
        if cur <= floor:
            return Decision(obs.seq, ACTION_HOLD, cur, cur, "at_floor")
        if self.gate.down_in_cooldown(now):
            return Decision(obs.seq, ACTION_HOLD, cur, cur,
                            f"down_cooldown {reason}")
        if self.gate.flap_blocked(ACTION_DOWN, now):
            return Decision(obs.seq, ACTION_HOLD, cur, cur,
                            f"flap_damped {reason}")
        nxt = self._step_down(cur)
        if nxt is None or nxt < floor:
            # the next quantum undershoots the floor: land on the
            # smallest legal count satisfying it instead (a raw clamp
            # to `floor` could emit a slice-illegal target)
            nxt = self.legalize_up(floor)
        if nxt >= cur:
            return Decision(obs.seq, ACTION_HOLD, cur, cur, "at_floor")
        return Decision(obs.seq, ACTION_DOWN, cur, nxt, reason)
