"""TPU slice topology math.

This module encodes the constraint the reference never had to face (SURVEY §7
"hard parts"): on TPU, a worker replica is a *host* in a pod slice, hosts come in
fixed chips-per-host quanta, and only certain slice topologies exist. So:

* gang PodGroup ``MinMember`` = ``hosts_per_slice(accelerator, topology)``;
* elastic rescale may only land on ``legal_host_counts`` — the reference's
  free-form replica doubling (torchelastic job.go:102-104) is snapped to the
  nearest legal quantum by ``next_legal_host_count``.

The tables mirror GKE's published accelerator/topology matrix and are data —
extendable without code changes.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# accelerator name (cloud.google.com/gke-tpu-accelerator value) →
#   (chips per host, legal topology strings)
_ACCELERATORS: Dict[str, Tuple[int, List[str]]] = {
    # v5e single-host device types: whole slice on one VM.
    "tpu-v5-lite-device": (8, ["1x1", "2x2", "2x4"]),
    # v5e pod slices: 4 chips per host, 2D torus.
    "tpu-v5-lite-podslice": (
        4,
        ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"],
    ),
    # v4 pod slices: 4 chips per host, 3D torus.
    "tpu-v4-podslice": (
        4,
        ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8", "4x8x8", "8x8x8",
         "8x8x12", "8x8x16", "8x16x16"],
    ),
    # v5p: 4 chips per host, 3D torus.
    "tpu-v5p-slice": (
        4,
        ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8", "4x8x8", "8x8x8",
         "8x8x16", "8x16x16", "16x16x16"],
    ),
    # v6e (Trillium): 2D, 4 chips per host multi-host, up to 256 chips.
    "tpu-v6e-slice": (
        4,
        ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"],
    ),
}

_SINGLE_HOST_MAX_CHIPS = {
    # Slices at or under this many chips fit one host (e.g. v5e ct5lp-hightpu-8t).
    "tpu-v5-lite-podslice": 4,
    "tpu-v5-lite-device": 8,
    "tpu-v6e-slice": 4,
}


@dataclass(frozen=True)
class SliceShape:
    accelerator: str
    topology: str

    @property
    def chips(self) -> int:
        return chips_in_topology(self.topology)

    @property
    def hosts(self) -> int:
        return hosts_per_slice(self.accelerator, self.topology)

    @property
    def chips_per_host(self) -> int:
        return chips_per_host(self.accelerator)


def parse_topology(topology: str) -> Tuple[int, ...]:
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"malformed topology {topology!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"malformed topology {topology!r}")
    return dims


def chips_in_topology(topology: str) -> int:
    return math.prod(parse_topology(topology))


def chips_per_host(accelerator: str) -> int:
    spec = _ACCELERATORS.get(accelerator)
    if spec is None:
        raise KeyError(f"unknown TPU accelerator {accelerator!r}")
    return spec[0]


def legal_topologies(accelerator: str) -> List[str]:
    spec = _ACCELERATORS.get(accelerator)
    if spec is None:
        raise KeyError(f"unknown TPU accelerator {accelerator!r}")
    return list(spec[1])


def hosts_per_slice(accelerator: str, topology: str) -> int:
    """Host (VM) count of one slice — the gang MinMember for its worker group."""
    chips = chips_in_topology(topology)
    single_max = _SINGLE_HOST_MAX_CHIPS.get(accelerator)
    if single_max is not None and chips <= single_max:
        return 1
    per_host = chips_per_host(accelerator)
    return max(1, math.ceil(chips / per_host))


def legal_host_counts(accelerator: str) -> List[int]:
    """Sorted unique host counts reachable via legal topologies — the elastic
    rescale quanta."""
    counts = {hosts_per_slice(accelerator, t) for t in legal_topologies(accelerator)}
    return sorted(counts)


def topology_for_hosts(accelerator: str, hosts: int) -> Optional[str]:
    """Smallest legal topology providing at least ``hosts`` hosts (None if the
    accelerator tops out below that)."""
    best: Optional[Tuple[int, str]] = None
    for t in legal_topologies(accelerator):
        h = hosts_per_slice(accelerator, t)
        if h >= hosts and (best is None or h < best[0]):
            best = (h, t)
    return best[1] if best else None


def next_legal_host_count(
    accelerator: str, current: int, *, direction: int = +1
) -> Optional[int]:
    """Next legal host count strictly above (direction=+1) or below (-1)
    ``current``; None at the boundary. Used by the elastic autoscaler in place of
    the reference's unconstrained ``replicas *= 2``."""
    counts = legal_host_counts(accelerator)
    if direction > 0:
        for c in counts:
            if c > current:
                return c
        return None
    for c in reversed(counts):
        if c < current:
            return c
    return None


def snap_host_count(accelerator: str, desired: int) -> int:
    """Snap an arbitrary desired host count to the nearest legal quantum
    (rounding up, capped at the largest legal topology)."""
    counts = legal_host_counts(accelerator)
    for c in counts:
        if c >= desired:
            return c
    return counts[-1]


def validate_slice(accelerator: str, topology: str) -> None:
    if topology not in legal_topologies(accelerator):
        raise ValueError(
            f"topology {topology!r} is not legal for {accelerator!r}; "
            f"legal: {legal_topologies(accelerator)}"
        )
