"""TPU slice topology math.

This module encodes the constraint the reference never had to face (SURVEY §7
"hard parts"): on TPU, a worker replica is a *host* in a pod slice, hosts come in
fixed chips-per-host quanta, and only certain slice topologies exist. So:

* gang PodGroup ``MinMember`` = ``hosts_per_slice(accelerator, topology)``;
* elastic rescale may only land on ``legal_host_counts`` — the reference's
  free-form replica doubling (torchelastic job.go:102-104) is snapped to the
  nearest legal quantum by ``next_legal_host_count``.

The tables mirror GKE's published accelerator/topology matrix and are data —
extendable without code changes.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# accelerator name (cloud.google.com/gke-tpu-accelerator value) →
#   (chips per host, legal topology strings)
_ACCELERATORS: Dict[str, Tuple[int, List[str]]] = {
    # v5e single-host device types: whole slice on one VM.
    "tpu-v5-lite-device": (8, ["1x1", "2x2", "2x4"]),
    # v5e pod slices: 4 chips per host, 2D torus.
    "tpu-v5-lite-podslice": (
        4,
        ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"],
    ),
    # v4 pod slices: 4 chips per host, 3D torus.
    "tpu-v4-podslice": (
        4,
        ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8", "4x8x8", "8x8x8",
         "8x8x12", "8x8x16", "8x16x16"],
    ),
    # v5p: 4 chips per host, 3D torus.
    "tpu-v5p-slice": (
        4,
        ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8", "4x8x8", "8x8x8",
         "8x8x16", "8x16x16", "16x16x16"],
    ),
    # v6e (Trillium): 2D, 4 chips per host multi-host, up to 256 chips.
    "tpu-v6e-slice": (
        4,
        ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"],
    ),
}

_SINGLE_HOST_MAX_CHIPS = {
    # Slices at or under this many chips fit one host (e.g. v5e ct5lp-hightpu-8t).
    "tpu-v5-lite-podslice": 4,
    "tpu-v5-lite-device": 8,
    "tpu-v6e-slice": 4,
}


@dataclass(frozen=True)
class SliceShape:
    accelerator: str
    topology: str

    @property
    def chips(self) -> int:
        return chips_in_topology(self.topology)

    @property
    def hosts(self) -> int:
        return hosts_per_slice(self.accelerator, self.topology)

    @property
    def chips_per_host(self) -> int:
        return chips_per_host(self.accelerator)


def parse_topology(topology: str) -> Tuple[int, ...]:
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"malformed topology {topology!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"malformed topology {topology!r}")
    return dims


def chips_in_topology(topology: str) -> int:
    return math.prod(parse_topology(topology))


def chips_per_host(accelerator: str) -> int:
    spec = _ACCELERATORS.get(accelerator)
    if spec is None:
        raise KeyError(f"unknown TPU accelerator {accelerator!r}")
    return spec[0]


def legal_topologies(accelerator: str) -> List[str]:
    spec = _ACCELERATORS.get(accelerator)
    if spec is None:
        raise KeyError(f"unknown TPU accelerator {accelerator!r}")
    return list(spec[1])


def hosts_per_slice(accelerator: str, topology: str) -> int:
    """Host (VM) count of one slice — the gang MinMember for its worker group."""
    chips = chips_in_topology(topology)
    single_max = _SINGLE_HOST_MAX_CHIPS.get(accelerator)
    if single_max is not None and chips <= single_max:
        return 1
    per_host = chips_per_host(accelerator)
    return max(1, math.ceil(chips / per_host))


def legal_host_counts(accelerator: str) -> List[int]:
    """Sorted unique host counts reachable via legal topologies — the elastic
    rescale quanta."""
    counts = {hosts_per_slice(accelerator, t) for t in legal_topologies(accelerator)}
    return sorted(counts)


def topology_for_hosts(accelerator: str, hosts: int) -> Optional[str]:
    """Smallest legal topology providing at least ``hosts`` hosts (None if the
    accelerator tops out below that)."""
    best: Optional[Tuple[int, str]] = None
    for t in legal_topologies(accelerator):
        h = hosts_per_slice(accelerator, t)
        if h >= hosts and (best is None or h < best[0]):
            best = (h, t)
    return best[1] if best else None


def next_legal_host_count(
    accelerator: str, current: int, *, direction: int = +1
) -> Optional[int]:
    """Next legal host count strictly above (direction=+1) or below (-1)
    ``current``; None at the boundary. Used by the elastic autoscaler in place of
    the reference's unconstrained ``replicas *= 2``."""
    counts = legal_host_counts(accelerator)
    if direction > 0:
        for c in counts:
            if c > current:
                return c
        return None
    for c in reversed(counts):
        if c < current:
            return c
    return None


def snap_host_count(accelerator: str, desired: int) -> int:
    """Snap an arbitrary desired host count to the nearest legal quantum
    (rounding up, capped at the largest legal topology)."""
    counts = legal_host_counts(accelerator)
    for c in counts:
        if c >= desired:
            return c
    return counts[-1]


def validate_slice(accelerator: str, topology: str) -> None:
    if topology not in legal_topologies(accelerator):
        raise ValueError(
            f"topology {topology!r} is not legal for {accelerator!r}; "
            f"legal: {legal_topologies(accelerator)}"
        )


# --------------------------------------------------------------- mesh shapes
# The elastic decision is no longer just a host count: a live reshard
# (tpu_on_k8s/parallel/reshard.py) needs the *(hosts, mesh shape)* pair,
# where the mesh shape is the logical axis layout the training state is
# repartitioned onto. The legality constraint is chips, not hosts: the
# axis sizes must multiply to the slice configuration's chip count —
# the same quanta rule `parallel/mesh.MeshConfig.resolve` enforces on
# the compute plane, expressed here dependency-free so the controller
# can validate a decision without importing jax.

def format_mesh_axes(mesh: Dict[str, int]) -> str:
    """Stable wire form of a mesh shape ("data=2,fsdp=8"): sorted,
    trivial (size-1) axes dropped — two writers of the same shape
    produce identical strings. "" is the single-chip/trivial mesh."""
    return ",".join(f"{a}={int(s)}" for a, s in sorted(mesh.items())
                    if int(s) > 1)


def parse_mesh_axes(raw: str) -> Dict[str, int]:
    """Inverse of ``format_mesh_axes``. Raises ValueError on malformed
    input (non-numeric or non-positive sizes) — callers on annotation
    paths catch and treat as "no request"."""
    out: Dict[str, int] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        axis, _, size = part.partition("=")
        if not axis or not size:
            raise ValueError(f"malformed mesh axes {raw!r}")
        n = int(size)
        if n < 1:
            raise ValueError(f"non-positive axis size in {raw!r}")
        out[axis] = n
    return out


def slice_chips(accelerator: str, topology: str, num_slices: int = 1) -> int:
    """Total chips of a slice configuration — the budget a mesh shape
    must multiply to."""
    validate_slice(accelerator, topology)
    return chips_in_topology(topology) * max(int(num_slices), 1)


def validate_mesh_for_slice(accelerator: str, topology: str,
                            mesh: Dict[str, int],
                            num_slices: int = 1) -> None:
    """A mesh shape is slice-legal iff its axis product equals the slice
    configuration's chip count. Raises ValueError naming both numbers —
    the decision-side guard matching the compute plane's
    ``MeshConfig.resolve`` check."""
    chips = slice_chips(accelerator, topology, num_slices)
    product = math.prod(max(int(s), 1) for s in mesh.values()) if mesh else 1
    if product != chips:
        raise ValueError(
            f"mesh shape {format_mesh_axes(mesh) or 'single'} has axis "
            f"product {product} but {accelerator}/{topology}"
            f"{f' x{num_slices}' if num_slices > 1 else ''} provides "
            f"{chips} chips — axis sizes must multiply to the chip count")


def mesh_shape_for_slice(accelerator: str, topology: str,
                         num_slices: int = 1, *, data: int = 1,
                         model: int = 1, expert: int = 1,
                         ) -> Dict[str, int]:
    """The default (hosts, mesh shape) second half for a slice
    configuration: fixed axes as given, ``fsdp`` absorbing the remaining
    chips (the training plane's default parallelism). Raises ValueError
    when the fixed axes do not divide the chip count."""
    chips = slice_chips(accelerator, topology, num_slices)
    fixed = max(int(data), 1) * max(int(model), 1) * max(int(expert), 1)
    if chips % fixed != 0:
        raise ValueError(
            f"fixed axes data={data},model={model},expert={expert} "
            f"(product {fixed}) do not divide the {chips} chips of "
            f"{accelerator}/{topology}"
            f"{f' x{num_slices}' if num_slices > 1 else ''}")
    shape = {"data": int(data), "fsdp": chips // fixed,
             "model": int(model), "expert": int(expert)}
    validate_mesh_for_slice(accelerator, topology, shape, num_slices)
    return shape


def format_reshard_spec(generation: int, hosts: int,
                        mesh: Dict[str, int]) -> str:
    """The annotation wire form of a (hosts, mesh shape) rescale
    decision: ``gen=3;hosts=4;mesh=data=2,fsdp=8``
    (``ANNOTATION_RESHARD_REQUESTED_SPEC``). Order-normalized so two
    writers of the same decision produce identical strings."""
    return (f"gen={int(generation)};hosts={int(hosts)};"
            f"mesh={format_mesh_axes(mesh)}")


def parse_reshard_spec(raw: str) -> Optional[Tuple[int, int, Dict[str, int]]]:
    """Inverse of ``format_reshard_spec``: (generation, hosts,
    mesh_axes), or None on malformed input — a garbled annotation must
    read as "no request", never crash a poll loop."""
    try:
        fields = dict(part.split("=", 1) for part in raw.split(";") if part)
        gen = int(fields["gen"])
        hosts = int(fields["hosts"])
        mesh = parse_mesh_axes(fields.get("mesh", ""))
    except (KeyError, ValueError):
        return None
    if gen < 0 or hosts < 1:
        return None
    return gen, hosts, mesh
