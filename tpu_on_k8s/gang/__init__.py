"""Gang scheduling (L4): atomic TPU-slice allocation.

Analog of /root/reference/pkg/gangscheduler/ with the TPU-specific twist that
PodGroup MinMember derives from slice host count (``tpu_on_k8s.gang.topology``).
"""

from tpu_on_k8s.gang.scheduler import (
    GANG_SCHEDULER_NAME,
    GangRegistry,
    PodGroup,
    SliceGangAdmission,
    SliceGangScheduler,
    default_registry,
    podgroup_name,
)
from tpu_on_k8s.gang.topology import (
    SliceShape,
    chips_in_topology,
    chips_per_host,
    hosts_per_slice,
    legal_host_counts,
    next_legal_host_count,
    topology_for_hosts,
)
