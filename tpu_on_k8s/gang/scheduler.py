"""Gang scheduling: PodGroup objects, the scheduler seam, and its registry.

Analog of /root/reference/pkg/gangscheduler/ — the ``GangScheduler`` contract
(interface.go:31-48), the name-keyed registry (registry/registry.go:36-48), and
a slice-aware scheduler playing Volcano's role (volcano/volcano.go):

* per-task-type podgroups when DAGScheduling is on (generatePodGroupsByRole,
  volcano.go:109-172), else one job-wide podgroup (generatePodGroupsByJob,
  volcano.go:175-230);
* TPU twist (SURVEY §2.10, §7): for Worker groups, ``min_member`` is the **slice
  host count** × num_slices — a TPU slice is atomic, so admitting fewer hosts
  than the slice topology needs can never make progress;
* ``min_resources`` is scaled to min_member when a MinAvailable override lowers
  it — fixing the reference's own TODO (volcano.go:223-227);
* AIMaster pods stay on the default scheduler (volcano.go:240-243) — they hold
  no TPU chips and must outlive gang preemption.

The in-memory ``SliceGangAdmission`` stands in for the external Volcano binary:
it atomically flips a whole podgroup's pods to schedulable once the gang is
complete, which is what tests and the local driver observe.
"""
from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import ObjectMeta, OwnerReference, Pod
from tpu_on_k8s.api.types import SchedulingPolicy, TaskType, TPUJob
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    InMemoryCluster,
    NotFoundError,
)
from tpu_on_k8s.gang import topology
from tpu_on_k8s.utils import resources as resmath

GANG_SCHEDULER_NAME = "tpu-slice"

# Marks podgroups whose admission consumes TPU slices from the pool
# inventory (worker per-role gangs and job-wide gangs; coordinator-role
# groups hold no slices).
LABEL_SLICE_GANG = f"{constants.API_GROUP}/slice-gang"


@dataclass
class PodGroupSpec:
    """Volcano PodGroupSpec analog (volcano.sh/apis scheduling/v1beta1)."""

    min_member: int = 1
    min_resources: Dict[str, float] = field(default_factory=dict)
    queue: str = ""
    priority_class_name: str = ""


@dataclass
class PodGroupStatus:
    phase: str = "Pending"  # Pending | Inqueue | Running
    admitted: int = 0


@dataclass
class PodGroup:
    api_version: str = "scheduling.distributed.tpu.io/v1beta1"
    kind: str = "PodGroup"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)


def slice_quorum(job: TPUJob) -> int:
    """Full slice host complement: hosts_per_slice × num_slices. The atomic
    admission unit for the job's worker gang."""
    tpu = job.spec.tpu_policy
    return topology.hosts_per_slice(tpu.accelerator, tpu.topology) * max(tpu.num_slices, 1)


def validate_gang_feasibility(job: TPUJob) -> None:
    """Reject statically-deadlocked gangs: a worker group smaller than the
    slice quorum can never be admitted (fewer pods will ever exist than
    min_member requires), so surface it as a job failure instead of a
    silently forever-Pending podgroup."""
    task = job.spec.tasks.get(TaskType.WORKER)
    if task is None:
        return
    quorum = slice_quorum(job)
    if task.num_tasks < quorum:
        raise ValueError(
            f"worker num_tasks={task.num_tasks} is below the slice quorum "
            f"{quorum} (hosts_per_slice × num_slices for "
            f"{job.spec.tpu_policy.accelerator}/{job.spec.tpu_policy.topology} "
            f"× {job.spec.tpu_policy.num_slices}); the gang could never admit")


def podgroup_name(job: TPUJob, task_type: Optional[TaskType] = None) -> str:
    """Job-wide ``{name}-{uid5}`` / per-role ``{name}-{role}-{uid5}``
    (volcano.go name scheme)."""
    uid5 = job.metadata.uid[:5]
    if task_type is None:
        return f"{job.metadata.name}-{uid5}"
    return f"{job.metadata.name}-{task_type.value.lower()}-{uid5}"


class SliceGangScheduler:
    """The Volcano-adapter analog, targeting the in-memory cluster. A GKE
    backend would emit the same PodGroup shapes as real Volcano CRs."""

    def __init__(self, cluster: InMemoryCluster, *, per_role: bool = True) -> None:
        self.cluster = cluster
        self.per_role = per_role

    def name(self) -> str:
        return GANG_SCHEDULER_NAME

    # ------------------------------------------------------------------ helpers
    @staticmethod
    def _scheduling_policy(job: TPUJob) -> SchedulingPolicy:
        return job.spec.run_policy.scheduling_policy or SchedulingPolicy()

    def _owner_ref(self, job: TPUJob) -> OwnerReference:
        return OwnerReference(
            api_version=job.api_version, kind=job.kind, name=job.metadata.name,
            uid=job.metadata.uid, controller=True, block_owner_deletion=True)

    def _min_member_for_task(self, job: TPUJob, task_type: TaskType) -> int:
        """Per-role gang quorum. Worker groups are slice-atomic: quorum is
        never below the full slice host complement (hosts_per_slice ×
        num_slices) even if a user MinMembers override asks for less — a
        partial slice cannot initialize its ICI mesh. A user override may only
        raise it. Other roles honor user MinMembers (volcano.go:127-131)."""
        task = job.spec.tasks[task_type]
        policy = self._scheduling_policy(job)
        user_min = policy.min_members.get(task_type)
        if task_type is TaskType.WORKER:
            quorum = slice_quorum(job)
            return max(user_min if user_min is not None else task.num_tasks, quorum)
        if user_min is not None:
            return min(user_min, task.num_tasks) if task.num_tasks else user_min
        return task.num_tasks

    # ---------------------------------------------------------------- interface
    def create_podgroups(self, job: TPUJob) -> None:
        """CreatePodGroup (volcano.go:61-106): idempotent create of the job's
        podgroup(s)."""
        policy = self._scheduling_policy(job)
        if self.per_role:
            for task_type, task in job.spec.tasks.items():
                if task_type is TaskType.AIMASTER:
                    # AIMaster never binds to a gang (bind_pod exempts it) —
                    # creating a group for it would orphan a forever-Pending
                    # podgroup (reference skips it too, volcano.go:116-117).
                    continue
                min_member = self._min_member_for_task(job, task_type)
                # MinResources scaled to min_member (fixes volcano.go:223-227).
                # TPU chips are injected per-pod by SetClusterSpec at create
                # time (tpujob.py:128-131), so the gang's resource claim must
                # count them too — admission capacity keys on this.
                per_pod = dict(resmath.pod_requests(task.template.spec))
                per_pod.setdefault(
                    constants.RESOURCE_TPU,
                    topology.chips_per_host(job.spec.tpu_policy.accelerator))
                self._ensure(job, podgroup_name(job, task_type), PodGroupSpec(
                    min_member=min_member,
                    min_resources=resmath.scale(per_pod, min_member),
                    queue=policy.queue,
                    priority_class_name=policy.priority_class_name,
                ), task_type=task_type,
                    slice_gang=task_type is TaskType.WORKER)
            return
        # Job-wide group: all tasks except AIMaster (volcano.go:186-196).
        total = sum(t.num_tasks for tt, t in job.spec.tasks.items()
                    if tt is not TaskType.AIMASTER)
        min_member = total
        if policy.min_available is not None:
            min_member = min(policy.min_available, total)
        req = {}
        for tt, t in job.spec.tasks.items():
            if tt is TaskType.AIMASTER:
                continue
            req = resmath.add(req, resmath.task_requests(t))
        # chips injected per-pod by SetClusterSpec count toward the gang claim
        req = resmath.add(req, {constants.RESOURCE_TPU: total * topology.
                                chips_per_host(job.spec.tpu_policy.accelerator)})
        if 0 < min_member < total and total > 0:
            req = resmath.scale(req, min_member / total)
        # the job-wide gang holds the workers, so it consumes slices
        self._ensure(job, podgroup_name(job), PodGroupSpec(
            min_member=min_member, min_resources=req, queue=policy.queue,
            priority_class_name=policy.priority_class_name), slice_gang=True)

    def _ensure(self, job: TPUJob, name: str, spec: PodGroupSpec,
                task_type: Optional[TaskType] = None,
                slice_gang: bool = False) -> None:
        labels = {constants.LABEL_JOB_NAME: job.metadata.name}
        if task_type is not None:
            labels[constants.LABEL_TASK_TYPE] = task_type.value.lower()
        if slice_gang:
            labels[LABEL_SLICE_GANG] = "true"
        existing = self.cluster.try_get(PodGroup, job.metadata.namespace, name)
        if existing is not None:
            if existing.spec != spec:
                def mutate(pg: PodGroup) -> None:
                    pg.spec = spec
                try:
                    self.cluster.update_with_retry(
                        PodGroup, job.metadata.namespace, name, mutate)
                except NotFoundError:
                    pass
            missing = {k: v for k, v in labels.items()
                       if existing.metadata.labels.get(k) != v}
            if missing:
                # backfill (pre-existing groups from an older manager must
                # not silently bypass the capacity gate)
                try:
                    self.cluster.patch_meta(PodGroup, job.metadata.namespace,
                                            name, labels=missing)
                except NotFoundError:
                    pass
            return
        pg = PodGroup(
            metadata=ObjectMeta(
                name=name, namespace=job.metadata.namespace,
                labels=labels,
                owner_references=[self._owner_ref(job)]),
            spec=spec)
        try:
            self.cluster.create(pg)
        except AlreadyExistsError:
            pass

    def bind_pod(self, job: TPUJob, pod: Pod, task_type: TaskType) -> None:
        """BindPodToPodGroup (volcano.go:238-287): group annotation + scheduler
        delegation. AIMaster keeps the default scheduler (volcano.go:240-243)."""
        if task_type is TaskType.AIMASTER:
            return
        name = podgroup_name(job, task_type if self.per_role else None)
        pod.metadata.annotations[constants.ANNOTATION_GANG_GROUP_NAME] = name
        pod.spec.scheduler_name = GANG_SCHEDULER_NAME

    def delete_podgroups(self, job: TPUJob) -> None:
        for pg in self.cluster.list(PodGroup, job.metadata.namespace,
                                    {constants.LABEL_JOB_NAME: job.metadata.name}):
            try:
                self.cluster.delete(PodGroup, pg.metadata.namespace, pg.metadata.name)
            except NotFoundError:
                pass


@dataclass(frozen=True)
class NodePool:
    """A GKE TPU node pool: ``num_slices`` independent slices of
    ``accelerator``/``topology``, each slice being ``hosts_per_slice``
    accelerator/topology-labeled nodes. The finite inventory the Volcano
    analog allocates from (VERDICT round 1 #6 — admission was previously an
    unconstrained ``node-N`` string generator).

    ``cpu_per_host`` / ``memory_per_host`` bound the non-TPU resources of
    each host (0 = unconstrained): admission compares the gang's per-pod
    ``min_resources`` share against them (the reference delegates the same
    check to Volcano's cluster-capacity filter, volcano.go:175-230), so a
    gang can fit by slice count yet still wait on CPU/memory."""

    name: str
    accelerator: str
    topology: str
    num_slices: int
    cpu_per_host: float = 0.0
    memory_per_host: float = 0.0

    @property
    def hosts_per_slice(self) -> int:
        return topology.hosts_per_slice(self.accelerator, self.topology)

    def node_name(self, slice_idx: int, host_idx: int) -> str:
        return f"{self.name}-s{slice_idx}-h{host_idx}"

    def matches(self, accelerator: str, topo: str) -> bool:
        return self.accelerator == accelerator and self.topology == topo

    def fits_per_pod(self, per_pod: Dict[str, float]) -> bool:
        """One worker pod per TPU host (the GKE TPU model): the pod's CPU and
        memory share must fit a single host's capacity."""
        if self.cpu_per_host and per_pod.get("cpu", 0.0) > self.cpu_per_host:
            return False
        if (self.memory_per_host
                and per_pod.get("memory", 0.0) > self.memory_per_host):
            return False
        return True


def parse_node_pools(spec: str) -> List[NodePool]:
    """Parse the ``--node-pools`` flag: comma-separated
    ``name=accelerator:topology:num_slices[:cpu=C][:mem=M]`` entries, e.g.
    ``poolA=tpu-v5-lite-podslice:4x4:2:cpu=96:mem=384e9``."""
    pools: List[NodePool] = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        name, _, rest = entry.partition("=")
        if not rest:
            raise ValueError(f"node pool {entry!r}: expected name=acc:topo:n")
        parts = rest.split(":")
        if len(parts) < 3:
            raise ValueError(f"node pool {entry!r}: expected acc:topo:n")
        acc, topo, n = parts[0], parts[1], int(parts[2])
        cpu = mem = 0.0
        for extra in parts[3:]:
            k, _, v = extra.partition("=")
            if k == "cpu":
                cpu = float(v)
            elif k == "mem":
                mem = float(v)
            else:
                raise ValueError(f"node pool {entry!r}: unknown option {k!r}")
        topology.validate_slice(acc, topo)  # fail loudly at flag-parse time
        pools.append(NodePool(name=name, accelerator=acc, topology=topo,
                              num_slices=n, cpu_per_host=cpu,
                              memory_per_host=mem))
    return pools


def load_node_pools_file(path: str) -> List[NodePool]:
    """Load pools from YAML: a list of {name, accelerator, topology,
    numSlices, cpuPerHost?, memoryPerHost?} (the ConfigMap the scheduler
    Deployment mounts, config/scheduler/)."""
    import yaml

    with open(path) as f:
        raw = yaml.safe_load(f) or []
    pools = []
    for row in raw:
        acc = row["accelerator"]
        topo = row["topology"]
        topology.validate_slice(acc, topo)
        pools.append(NodePool(
            name=row["name"], accelerator=acc, topology=topo,
            num_slices=int(row.get("numSlices", row.get("num_slices", 1))),
            cpu_per_host=float(row.get("cpuPerHost",
                                       row.get("cpu_per_host", 0)) or 0),
            memory_per_host=float(row.get("memoryPerHost",
                                          row.get("memory_per_host", 0)) or 0)))
    return pools


class SliceGangAdmission:
    """In-memory stand-in for the Volcano scheduler binary: watches pods and
    podgroups; when a podgroup's full gang exists, admits them all atomically
    (flips phase to Inqueue/Running and stamps pod node names). One reconcile
    pass producing the whole gang — then one admission flipping it — is the
    north-star criterion (BASELINE.md).

    With ``pools`` configured, TPU worker gangs contend for a finite slice
    inventory: a gang admits only when its job's full ``num_slices``
    complement of matching slices is free (slices are atomic — partial
    allocation can never make progress), and the slices return to the pool
    when the podgroup goes away. Groups that request no TPU chips (master/
    coordinator roles) are capacity-unconstrained. Without pools the legacy
    unconstrained behavior is kept (pure protocol tests)."""

    def __init__(self, cluster: InMemoryCluster,
                 pools: Optional[List[NodePool]] = None) -> None:
        self.cluster = cluster
        self.pools = pools or []
        names = [p.name for p in self.pools]
        if len(set(names)) != len(names):
            # name-keyed inventory: a silent last-wins overwrite would hand
            # out slices from the wrong pool — refuse at construction
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate node pool names: {dupes}")
        self._lock = threading.Lock()
        self.admitted_groups: List[str] = []
        # "ns/group" -> [(pool_name, slice_idx), ...]
        self._allocations: Dict[str, List[tuple]] = {}
        self._free: Dict[str, List[int]] = {
            p.name: list(range(p.num_slices)) for p in (pools or [])}
        self._pool_by_name = {p.name: p for p in (pools or [])}
        # serializes the recovery REBUILD only (it does cluster I/O, so
        # it cannot run under the inventory lock); always acquired
        # before `_lock`, never after — no ordering cycle
        self._recover_lock = threading.Lock()
        self._recovered = not self.pools  # nothing to recover without pools
        # recover eagerly: free_slices()/metrics must never observe a
        # fully-free inventory while Running gangs still hold slices. A
        # transient API error here must not crash the process — the
        # scheduler loop's sync() retries on its next tick.
        if self.pools:
            try:
                self._ensure_recovered()
            # analyze: allow[silent-loss] startup recovery warns with exc_info and retries on every sync() tick
            except Exception:
                from tpu_on_k8s.utils.logging import get_logger
                get_logger("slicescheduler").warning(
                    "allocation recovery failed at startup; retrying in "
                    "sync()", exc_info=True)

    def _ensure_recovered(self) -> None:
        """Run recovery exactly once, even when the scheduler-loop tick
        and a leadership-takeover resync() race here: the flag is read
        and latched under the inventory lock, the rebuild itself under
        the recovery lock (double-checked — the loser of the race must
        not rebuild a second time over fresh allocations)."""
        with self._lock:
            if self._recovered:
                return
        with self._recover_lock:
            with self._lock:
                if self._recovered:       # lost the race: already rebuilt
                    return
            self._recover_allocations()
            with self._lock:
                self._recovered = True

    def resync(self) -> None:
        """Drop the in-memory inventory and rebuild it from cluster state.
        Required on leadership takeover: allocations moved while this
        candidate was not leading, and admitting from a stale inventory is
        exactly the double-booking hazard leader election exists to stop.

        The clear runs under the RECOVERY lock too: clearing while a
        tick's in-flight ``_recover_allocations`` is mid-rebuild would
        erase the groups it already wrote, after which its
        ``_recovered = True`` latch makes the loss permanent — the
        over-reporting free_slices() this method exists to prevent."""
        with self._recover_lock:
            with self._lock:
                self._allocations.clear()
                self._free = {p.name: list(range(p.num_slices))
                              for p in self.pools}
                self._recovered = not self.pools
        self._ensure_recovered()

    def _recover_allocations(self) -> None:
        """Rebuild slice ownership after a scheduler restart: a Running
        slice-gang podgroup's pods carry pool-encoded node names
        (``{pool}-s{idx}-h{h}``) — without this, a restarted scheduler would
        re-offer held slices and double-book hosts."""
        # one pod list for the whole pass (not per group): over the REST
        # backend each list is an HTTP round-trip
        by_group = self._pods_by_group(None)
        for pg in self.cluster.list(PodGroup, None):
            if (pg.status.phase != "Running"
                    or pg.metadata.labels.get(LABEL_SLICE_GANG) != "true"):
                continue
            key = f"{pg.metadata.namespace}/{pg.metadata.name}"
            held: List[tuple] = []
            for pod in by_group.get(
                    (pg.metadata.namespace, pg.metadata.name), []):
                node = pod.spec.node_name or ""
                for pool in self.pools:
                    # exact per-pool pattern: a prefix match would let pool
                    # "tpu" claim nodes of pool "tpu-v5e"
                    m = re.fullmatch(
                        rf"{re.escape(pool.name)}-s(\d+)-h\d+", node)
                    if m:
                        alloc = (pool.name, int(m.group(1)))
                        if alloc not in held:
                            held.append(alloc)
                        break
            with self._lock:
                if held and key not in self._allocations:
                    self._allocations[key] = held
                    for pool_name, idx in held:
                        if idx in self._free.get(pool_name, []):
                            self._free[pool_name].remove(idx)

    # ----------------------------------------------------------- slice capacity
    def free_slices(self, pool_name: str) -> int:
        self._ensure_recovered()  # loud, never a wrong fully-free answer
        with self._lock:
            return len(self._free.get(pool_name, []))

    def _release_stale(self, namespace: Optional[str]) -> None:
        """Slices whose podgroup is gone return to the pool (job finished or
        deleted — the engine deletes podgroups on termination)."""
        live = {f"{pg.metadata.namespace}/{pg.metadata.name}"
                for pg in self.cluster.list(PodGroup, None)}
        with self._lock:
            for key in [k for k in self._allocations if k not in live]:
                for pool_name, idx in self._allocations.pop(key):
                    self._free[pool_name].append(idx)

    def _try_allocate(self, key: str, job: TPUJob,
                      pg: PodGroup) -> Optional[List[tuple]]:
        """All-or-nothing slice allocation for the job's tpu_policy. A pool
        must match the accelerator/topology, hold enough free slices, AND fit
        the gang's per-pod CPU/memory share on each host (resource-aware
        admission — a gang can fit by slice count yet wait on resources)."""
        tpu = job.spec.tpu_policy
        need = max(tpu.num_slices, 1)
        # Per-pod fit uses the WORKER task's own requests (+ the chips
        # SetClusterSpec injects), not min_resources/min_member — a job-wide
        # group averages master+worker requests, which could admit a gang
        # whose worker pods individually exceed a host.
        worker = job.spec.tasks.get(TaskType.WORKER)
        if worker is not None:
            per_pod = dict(resmath.pod_requests(worker.template.spec))
            per_pod.setdefault(constants.RESOURCE_TPU,
                               topology.chips_per_host(tpu.accelerator))
        else:
            per_pod = {k: v / max(pg.spec.min_member, 1)
                       for k, v in pg.spec.min_resources.items()}
        with self._lock:
            held = self._allocations.get(key)
            if held is not None:
                # An elastic rescale can change topology/num_slices under a
                # held allocation; slices of the wrong shape can never serve
                # the new gang — release and reallocate instead of handing
                # back stale hosts.
                shape_ok = (len(held) == need and all(
                    self._pool_by_name[pn].matches(tpu.accelerator,
                                                   tpu.topology)
                    for pn, _ in held))
                if shape_ok:
                    return held
                for pn, idx in self._allocations.pop(key):
                    self._free[pn].append(idx)
            for pool in self.pools:
                if not pool.matches(tpu.accelerator, tpu.topology):
                    continue
                if not pool.fits_per_pod(per_pod):
                    continue
                free = self._free[pool.name]
                if len(free) >= need:
                    taken = [(pool.name, free.pop(0)) for _ in range(need)]
                    self._allocations[key] = taken
                    return taken
        return None

    def _owner_job(self, pg: PodGroup) -> Optional[TPUJob]:
        for ref in pg.metadata.owner_references:
            if ref.kind == constants.KIND_TPUJOB:
                return self.cluster.try_get(TPUJob, pg.metadata.namespace,
                                            ref.name)
        return None

    # ----------------------------------------------------------------- admission
    def sync(self, namespace: Optional[str] = None) -> List[str]:
        """Admit every gang-complete podgroup (in creation order — the order
        the coordinator dequeued their jobs); returns names admitted this
        pass. Deterministic and pull-based so tests control timing.

        Running groups are revisited when any of their pods lack a node —
        an elastic rescale recreates pods under the same (still-Running)
        group, possibly with a different topology; those pods need nodes
        from a (possibly re-)allocated slice set."""
        self._ensure_recovered()  # retries a failed startup recovery
        if self.pools:
            self._release_stale(namespace)
        admitted = []
        # one pod list per pass (not per group): over the REST backend each
        # list is an HTTP round-trip and sync runs on a 100ms period
        by_group = self._pods_by_group(namespace)
        for pg in self.cluster.list(PodGroup, namespace):
            pods = by_group.get(
                (pg.metadata.namespace, pg.metadata.name), [])
            if (pg.status.phase == "Running"
                    and all(p.spec.node_name for p in pods)):
                continue
            if len(pods) < pg.spec.min_member:
                continue
            nodes: Optional[List[str]] = None
            if (self.pools
                    and pg.metadata.labels.get(LABEL_SLICE_GANG) == "true"
                    and pg.spec.min_resources.get(constants.RESOURCE_TPU)):
                job = self._owner_job(pg)
                if job is None:
                    continue
                key = f"{pg.metadata.namespace}/{pg.metadata.name}"
                taken = self._try_allocate(key, job, pg)
                if taken is None:
                    continue  # pool exhausted: gang waits, slices stay atomic
                nodes = [self._pool_by_name[pn].node_name(idx, h)
                         for pn, idx in taken
                         for h in range(self._pool_by_name[pn].hosts_per_slice)]

            def mutate(g: PodGroup) -> None:
                g.status.phase = "Running"
                g.status.admitted = len(pods)
            try:
                self.cluster.update_with_retry(
                    PodGroup, pg.metadata.namespace, pg.metadata.name, mutate,
                    subresource="status")
            except NotFoundError:
                continue
            with self._lock:
                self.admitted_groups.append(pg.metadata.name)
            admitted.append(pg.metadata.name)
            for i, pod in enumerate(pods):
                node = (nodes[i] if nodes is not None and i < len(nodes)
                        else f"tpu-node-{i}")
                self._assign_node(pod, node)
        return admitted

    def _pods_by_group(self, namespace: Optional[str]) -> Dict[tuple, List[Pod]]:
        """All gang-annotated pods, grouped by (namespace, group), each group
        sorted by pod name."""
        out: Dict[tuple, List[Pod]] = {}
        for pod in self.cluster.list(Pod, namespace):
            group = pod.metadata.annotations.get(
                constants.ANNOTATION_GANG_GROUP_NAME)
            if group:
                out.setdefault((pod.metadata.namespace, group), []).append(pod)
        for pods in out.values():
            pods.sort(key=lambda p: p.metadata.name)
        return out

    def _group_pods(self, pg: PodGroup) -> List[Pod]:
        return self._pods_by_group(pg.metadata.namespace).get(
            (pg.metadata.namespace, pg.metadata.name), [])

    def _assign_node(self, pod: Pod, node: str) -> None:
        if pod.spec.node_name:
            return

        def mutate(p: Pod) -> None:
            if not p.spec.node_name:
                p.spec.node_name = node
        try:
            self.cluster.update_with_retry(
                Pod, pod.metadata.namespace, pod.metadata.name, mutate)
        except NotFoundError:
            pass


class SliceSchedulerLoop:
    """The deployable admission actor: runs ``SliceGangAdmission.sync()`` on
    a period against any cluster backend (in-memory or REST). This is the
    process that plays Volcano's role in a deployment — the reference
    delegates admission to the external Volcano binary
    (volcano/volcano.go:238-287); here the slice scheduler is our own
    deliverable, started by ``main.py --enable-slice-scheduler`` (in-process
    with the manager) or ``--scheduler-only`` (its own Deployment,
    config/scheduler/)."""

    def __init__(self, admission: SliceGangAdmission,
                 period_seconds: float = 0.1) -> None:
        self.admission = admission
        self.period_seconds = period_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="slice-scheduler")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.admission.sync()
            # analyze: allow[silent-loss] scheduler loop survival; the failure is logged and the next tick retries
            except Exception:  # noqa: BLE001 — the loop must survive blips
                from tpu_on_k8s.utils.logging import get_logger
                get_logger("slicescheduler").exception("admission sync failed")
            self._stop.wait(self.period_seconds)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class GangRegistry:
    """Name-keyed scheduler registry (registry/registry.go:36-48)."""

    def __init__(self) -> None:
        self._schedulers: Dict[str, object] = {}

    def register(self, scheduler) -> None:
        self._schedulers[scheduler.name()] = scheduler

    def get(self, name: str):
        if name not in self._schedulers:
            raise KeyError(f"gang scheduler {name!r} not registered; "
                           f"have {sorted(self._schedulers)}")
        return self._schedulers[name]

    def names(self) -> List[str]:
        return sorted(self._schedulers)


def default_registry(cluster: InMemoryCluster, *, per_role: bool = True) -> GangRegistry:
    reg = GangRegistry()
    reg.register(SliceGangScheduler(cluster, per_role=per_role))
    return reg
