"""Job condition state machine helpers.

Analog of /root/reference/pkg/utils/utils.go:78-248: append/replace conditions with
transition filtering (mutually-exclusive Running/Restarting/Queuing handling,
``filterOutCondition`` utils.go:201-223), terminal-state predicates, and the
``{job}-{tasktype}-{index}`` naming convention.
"""
from __future__ import annotations

import datetime as _dt
from typing import Optional

from tpu_on_k8s.api.core import utcnow
from tpu_on_k8s.api.types import (
    JobCondition,
    JobConditionType,
    JobStatus,
    TaskType,
    TPUJob,
)


def gen_general_name(job_name: str, task_type: TaskType, index: int) -> str:
    """Pod/service name ``{job}-{type}-{idx}`` (reference utils.go:78-80)."""
    return f"{job_name}-{task_type.value.lower()}-{index}"


def get_condition(status: JobStatus, cond_type: JobConditionType) -> Optional[JobCondition]:
    for c in status.conditions:
        if c.type == cond_type:
            return c
    return None


def has_condition(status: JobStatus, cond_type: JobConditionType) -> bool:
    c = get_condition(status, cond_type)
    return c is not None and c.status == "True"


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.RUNNING)


def is_queuing(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.QUEUING)


def needs_coordinator_enqueue(status: JobStatus) -> bool:
    """A job enters the coordinator only before it first leaves Created/Queuing
    (reference utils.go:134-137 NeedEnqueueToCoordinator)."""
    if is_finished(status) or is_running(status):
        return False
    return not any(
        c.type in (JobConditionType.RUNNING, JobConditionType.RESTARTING)
        and c.status == "True"
        for c in status.conditions
    )


def update_job_conditions(
    status: JobStatus,
    cond_type: JobConditionType,
    reason: str = "",
    message: str = "",
    *,
    cond_status: str = "True",
    now: Optional[_dt.datetime] = None,
) -> bool:
    """Set ``cond_type`` on the status, demoting conflicting conditions
    (reference utils.go filterOutCondition semantics):

    * setting Running sets any Restarting/Queuing condition to "False";
    * setting Restarting demotes Running;
    * setting a terminal condition (Succeeded/Failed) demotes Running/Restarting.

    Returns True if anything changed.
    """
    now = now or utcnow()
    new = JobCondition(
        type=cond_type,
        status=cond_status,
        reason=reason,
        message=message,
        last_transition_time=now,
        last_update_time=now,
    )
    demote = {
        JobConditionType.RUNNING: {JobConditionType.RESTARTING, JobConditionType.QUEUING},
        JobConditionType.RESTARTING: {JobConditionType.RUNNING},
        JobConditionType.SUCCEEDED: {JobConditionType.RUNNING, JobConditionType.RESTARTING},
        JobConditionType.FAILED: {JobConditionType.RUNNING, JobConditionType.RESTARTING},
        JobConditionType.QUEUING: {JobConditionType.RUNNING},
    }.get(cond_type, set()) if cond_status == "True" else set()

    changed = False
    found = False
    for c in status.conditions:
        if c.type == cond_type:
            found = True
            if c.status != new.status or c.reason != reason or c.message != message:
                if c.status != new.status:
                    c.last_transition_time = now
                c.status, c.reason, c.message = new.status, reason, message
                c.last_update_time = now
                changed = True
        elif c.type in demote and c.status == "True":
            c.status = "False"
            c.last_transition_time = now
            c.last_update_time = now
            changed = True
    if not found:
        status.conditions.append(new)
        changed = True
    return changed


def mark_created(job: TPUJob) -> bool:
    return update_job_conditions(
        job.status,
        JobConditionType.CREATED,
        reason="JobCreated",
        message=f"TPUJob {job.metadata.name} is created.",
    )
