"""Resource math over plain quantity maps.

Analog of /root/reference/pkg/utils/resources/resources.go: pod requests are
``max(max(init containers), sum(containers))``; task/job requests multiply by
replica counts; spot replicas can be split out (JobResourceRequests,
resources.go:89-109). Quantities here are numeric (chips/cores/bytes), see
``tpu_on_k8s.api.core.ResourceRequirements``.
"""
from __future__ import annotations

from typing import Dict, Mapping, Optional

from tpu_on_k8s.api.core import PodSpec
from tpu_on_k8s.api.types import TaskSpec, TPUJob

ResourceList = Dict[str, float]


def add(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    out: ResourceList = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) + v
    return out


def scale(a: Mapping[str, float], factor: float) -> ResourceList:
    return {k: v * factor for k, v in a.items()}


def maximum(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    out: ResourceList = dict(a)
    for k, v in b.items():
        out[k] = max(out.get(k, 0.0), v)
    return out


def subtract(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    out: ResourceList = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) - v
    return out


def fits(request: Mapping[str, float], available: Mapping[str, float]) -> bool:
    """True if ``request`` fits into ``available`` for every resource named in
    ``available`` (resources absent from ``available`` are unlimited — the
    ResourceQuota semantics the coordinator's quota plugin needs)."""
    return all(request.get(k, 0.0) <= v for k, v in available.items())


def pod_requests(spec: PodSpec) -> ResourceList:
    """Effective pod request: max(any single init container, sum of main
    containers) — k8s scheduling semantics the reference mirrors
    (resources.go init-container max)."""
    main: ResourceList = {}
    for c in spec.containers:
        main = add(main, c.resources.requests)
    init: ResourceList = {}
    for c in spec.init_containers:
        init = maximum(init, c.resources.requests)
    return maximum(main, init)


def task_requests(task: TaskSpec, replicas: Optional[int] = None) -> ResourceList:
    n = task.num_tasks if replicas is None else replicas
    return scale(pod_requests(task.template.spec), n)


def job_requests(job: TPUJob, *, include_spot: bool = True) -> ResourceList:
    """Total job request (JobResourceRequests, resources.go:89-109); with
    ``include_spot=False`` spot replicas are excluded (the reference reports
    them separately in QueueUnit.SpotResources)."""
    total: ResourceList = {}
    for task in job.spec.tasks.values():
        n = task.num_tasks
        if not include_spot and task.spot_task_spec is not None:
            n = max(0, n - task.spot_task_spec.num_spot_tasks)
        total = add(total, task_requests(task, n))
    return total


def job_spot_requests(job: TPUJob) -> ResourceList:
    total: ResourceList = {}
    for task in job.spec.tasks.values():
        spot = task.spot_task_spec
        if spot is None or spot.num_spot_tasks <= 0:
            continue
        n = min(task.num_tasks, spot.num_spot_tasks)
        total = add(total, task_requests(task, n))
    return total
