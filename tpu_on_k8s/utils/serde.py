"""Dataclass <-> plain-dict (de)serialization for API objects.

The reference relies on k8s apimachinery's generated deepcopy/JSON marshalling
(/root/reference/apis/*/v1alpha1/zz_generated.deepcopy.go). Here a single generic
reflective codec replaces all of that: every API dataclass round-trips through
``to_dict`` / ``from_dict`` (used by the in-memory API server for deep-copy
semantics, by YAML manifest loading, and by tests).
"""
from __future__ import annotations

import dataclasses
import datetime as _dt
import enum
import typing
from typing import Any, Optional, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _type_hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    return hints


# k8s JSON tags that break the mechanical snake→camel rule (Go keeps
# initialisms upper-case: PodIP, HostIP, ClusterIP — k8s API conventions).
_CAMEL_OVERRIDES = {"pod_ip": "podIP", "host_ip": "hostIP",
                    "cluster_ip": "clusterIP"}


def _camel(name: str) -> str:
    """snake_case → camelCase for the k8s wire (api_version → apiVersion)."""
    override = _CAMEL_OVERRIDES.get(name)
    if override is not None:
        return override
    head, _, rest = name.partition("_")
    if not rest:
        return name
    return head + "".join(p[:1].upper() + p[1:] for p in rest.split("_"))


def to_dict(obj: Any, *, drop_none: bool = True, wire: bool = False) -> Any:
    """Recursively convert dataclasses/enums/datetimes into plain JSON-able data.

    ``wire=True`` emits camelCase keys for dataclass *fields* (the real
    Kubernetes JSON convention) while leaving plain-dict keys (labels,
    annotations, nodeSelector, resource names) untouched. Wire mode also
    applies the Kubernetes dialect rules a real apiserver enforces (pinned
    by the golden fixtures in tests/test_golden_wire.py):

    * ``metadata.resourceVersion`` is an opaque *string* on the wire, and is
      absent (never ``"0"``) on fresh objects;
    * timestamps serialize RFC 3339 with a ``Z`` suffix (metav1.Time);
    * classes may define ``__wire_out__(dict) -> dict`` /
      ``__wire_in__(dict) -> dict`` staticmethod hooks for shape adaptations
      the generic field walk can't express (e.g. core/v1's
      ``containerStatuses[].state.terminated`` nesting and tagged-union
      volume sources).
    """
    if obj is None:
        return None
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            raw = getattr(obj, f.name)
            if wire and f.name == "resource_version":
                if raw:
                    out["resourceVersion"] = str(raw)
                continue
            v = to_dict(raw, drop_none=drop_none, wire=wire)
            if drop_none and (v is None or v == {} or v == []):
                continue
            out[_camel(f.name) if wire else f.name] = v
        if wire:
            hook = getattr(type(obj), "__wire_out__", None)
            if hook is not None:
                out = hook(out)
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, _dt.datetime):
        if wire:
            # RFC 3339 requires an offset; a real apiserver's strict parse
            # rejects offset-less timestamps, so naive datetimes are treated
            # as UTC on the wire.
            if obj.tzinfo is None:
                obj = obj.replace(tzinfo=_dt.timezone.utc)
            return obj.isoformat().replace("+00:00", "Z")
        return obj.isoformat()
    if isinstance(obj, dict):
        # Keys go through conversion too: task maps are keyed by TaskType
        # enums. Plain string keys are data, never renamed.
        return {to_dict(k, drop_none=drop_none, wire=wire):
                to_dict(v, drop_none=drop_none, wire=wire)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v, drop_none=drop_none, wire=wire) for v in obj]
    return obj


_QUANTITY_SUFFIX = {"m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
                    "P": 1e15, "E": 1e18, "Ki": 2**10, "Mi": 2**20,
                    "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}


def _parse_quantity(s: str) -> float:
    """k8s resource.Quantity string → float ("4"→4, "500m"→0.5, "20Gi"→…).

    Real apiservers serialize quantities (ResourceQuota hard/used, resource
    requests) as strings; internal maps are plain floats, so float-typed
    fields accept the wire form here."""
    s = s.strip()
    for suf in ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei",
                "m", "k", "M", "G", "T", "P", "E"):
        if s.endswith(suf):
            return float(s[:-len(suf)]) * _QUANTITY_SUFFIX[suf]
    return float(s)  # raises ValueError on junk, like any wire type error


def _construct(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    origin = get_origin(tp)
    if origin is typing.Union:  # Optional[X] and unions
        args = [a for a in get_args(tp) if a is not type(None)]
        for a in args:
            try:
                return _construct(a, data)
            except (TypeError, ValueError, KeyError):
                continue
        raise TypeError(f"cannot construct union {tp} from {data!r}")
    if origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        seq = [_construct(elem, v) for v in data]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        kt, vt = get_args(tp) or (Any, Any)
        return {_construct(kt, k): _construct(vt, v) for k, v in data.items()}
    if isinstance(tp, type):
        if dataclasses.is_dataclass(tp):
            return from_dict(tp, data)
        if issubclass(tp, enum.Enum):
            try:
                return tp(data)
            except ValueError:
                # Tolerate case variance in string enum values (e.g. YAML task
                # keys "master" vs "Master"), matching the reference's
                # normalization step (torchjob_defaults.go:33-45).
                if isinstance(data, str):
                    for member in tp:
                        if isinstance(member.value, str) and member.value.lower() == data.lower():
                            return member
                raise
        if tp is _dt.datetime and isinstance(data, str):
            # accept both RFC 3339 "Z" (what a real apiserver emits) and
            # "+00:00" (python isoformat)
            if data.endswith("Z"):
                data = data[:-1] + "+00:00"
            return _dt.datetime.fromisoformat(data)
        if tp is float and isinstance(data, (int, float)):
            return float(data)
        if tp is float and isinstance(data, str):
            return _parse_quantity(data)
        if tp is int and isinstance(data, str):
            # k8s serializes resourceVersion (and quantity-ish ints) as
            # opaque strings; accept numeric strings for int fields.
            s = data.strip()
            if s and s.lstrip("-").isdigit():
                return int(s)
            raise TypeError(f"expected int got non-numeric str {data!r}")
        if tp in (int, str, bool) and not isinstance(data, tp):
            raise TypeError(f"expected {tp} got {type(data)}")
    return data


def from_dict(cls: Type[T], data: Optional[dict]) -> T:
    """Reconstruct a dataclass instance (recursively) from plain data.

    Unknown keys are ignored (forward compatibility, like k8s JSON decoding).
    """
    if data is None:
        data = {}
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls} is not a dataclass")
    if not isinstance(data, dict):
        raise TypeError(f"cannot decode {cls.__name__} from {type(data).__name__} {data!r}")
    hook = getattr(cls, "__wire_in__", None)
    if hook is not None:
        data = hook(data)
    hints = _type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        # Accept both snake_case (internal) and camelCase (k8s wire) keys.
        key = f.name if f.name in data else _camel(f.name)
        if key in data:
            kwargs[f.name] = _construct(hints[f.name], data[key])
    return cls(**kwargs)


def deep_copy(obj: T) -> T:
    """Deep-copy an API dataclass via dict round-trip (the analog of
    zz_generated.deepcopy.go)."""
    return from_dict(type(obj), to_dict(obj, drop_none=False))
