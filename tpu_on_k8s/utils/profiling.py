"""Profiling hooks: XLA trace capture + profiler server (pprof analog).

The reference has no profiling at all (SURVEY.md §5.1). On TPU the tool is
the XLA profiler: ``trace("/dir")`` around training steps writes a
TensorBoard-loadable trace (MXU utilization, HBM traffic, collective
timelines); ``start_server(port)`` lets an external profiler attach live.
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional


def start_server(port: int):
    """Start the JAX profiler server (attach with TensorBoard / xprof)."""
    import jax

    return jax.profiler.start_server(port)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture an XLA trace of the enclosed steps into ``log_dir``."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up on the trace timeline."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
