"""Bounded-concurrency helpers (reference pkg/utils/concurrent/semaphore.go).

The reference bounds all bulk pod operations with a channel semaphore +
waitgroup (widths 10/50/100 — victim cleanup, failover deletes, scale
restarts). Here the same shape as a thread-pool map that a live GKE backend
uses for bulk API calls; the in-memory reconcilers stay synchronous.
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable, Iterable, List, Optional, Tuple, TypeVar

T = TypeVar("T")
R = TypeVar("R")


class Semaphore:
    """Counting semaphore + waitgroup in one (semaphore.go:21-45)."""

    def __init__(self, width: int):
        self._sem = threading.Semaphore(width)
        self._pending = 0
        self._lock = threading.Condition()

    def acquire(self) -> None:
        self._sem.acquire()
        with self._lock:
            self._pending += 1

    def release(self) -> None:
        self._sem.release()
        with self._lock:
            self._pending -= 1
            if self._pending == 0:
                self._lock.notify_all()

    def wait(self) -> None:
        """Block until every acquired slot has been released."""
        with self._lock:
            while self._pending:
                self._lock.wait()


def bounded_map(fn: Callable[[T], R], items: Iterable[T], width: int,
                ) -> List[Tuple[Optional[R], Optional[BaseException]]]:
    """Run ``fn`` over items with at most ``width`` in flight; returns
    (result, error) pairs in input order — bulk ops tolerate partial failure
    the way the reference's semaphore loops do."""
    items = list(items)
    out: List[Tuple[Optional[R], Optional[BaseException]]] = [(None, None)] * len(items)
    if not items:
        return out
    with concurrent.futures.ThreadPoolExecutor(max_workers=width) as pool:
        # analyze: allow[thread-roots] fn is this helper's parameter — each bounded_map CALLER is recorded as the spawn-through root with its real fn
        futures = {pool.submit(fn, item): i for i, item in enumerate(items)}
        for fut in concurrent.futures.as_completed(futures):
            i = futures[fut]
            try:
                out[i] = (fut.result(), None)
            # analyze: allow[silent-loss] the exception is returned to the caller in the (result, error) tuple
            except BaseException as e:  # noqa: BLE001 — collected, not raised
                out[i] = (None, e)
    return out
