"""Single structured logger for the whole framework.

The reference mixes four logging libraries (klog, logr/zap, logrus, glog —
SURVEY.md §5.1); here one key=value structured logger serves every component.
Built on stdlib logging so handlers/levels compose with host applications.
"""
from __future__ import annotations

import logging
import sys
from typing import Any

_ROOT = "tpu_on_k8s"


class KeyValueFormatter(logging.Formatter):
    """`ts level component msg key=value ...` — grep/loki-friendly."""

    def format(self, record: logging.LogRecord) -> str:
        base = (f"{self.formatTime(record, '%Y-%m-%dT%H:%M:%S')} "
                f"{record.levelname.lower()} {record.name} {record.getMessage()}")
        extras = getattr(record, "kv", None)
        if extras:
            base += " " + " ".join(f"{k}={v}" for k, v in extras.items())
        return base


def get_logger(component: str = "") -> logging.Logger:
    name = f"{_ROOT}.{component}" if component else _ROOT
    return logging.getLogger(name)


def configure(level: int = logging.INFO, stream=None) -> logging.Logger:
    """Idempotent root setup; returns the framework root logger."""
    root = logging.getLogger(_ROOT)
    root.setLevel(level)
    if not any(isinstance(h, logging.StreamHandler) for h in root.handlers):
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(KeyValueFormatter())
        root.addHandler(handler)
        root.propagate = False
    return root


def kv(logger: logging.Logger, level: int, msg: str, **fields: Any) -> None:
    """Structured emit: ``kv(log, logging.INFO, "scaled", job="j", hosts=8)``."""
    logger.log(level, msg, extra={"kv": fields})
