"""QPS-limited event recording, coalesced per object (reference
pkg/utils/flowcontrol/recorder.go:33-115).

Controllers can emit bursts of identical events (every reconcile of a stuck
job); the reference wraps its EventRecorder in a token bucket keyed by object
UID. Same here: a per-key token bucket in front of the cluster's
``record_event``, dropping (not queueing) excess — events are best-effort
diagnostics, backpressure would be worse.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional


class TokenBucket:
    def __init__(self, qps: float, burst: int,
                 clock: Callable[[], float] = time.monotonic):
        self.qps = qps
        self.burst = burst
        self.clock = clock
        self.tokens = float(burst)
        self.last = clock()

    def allow(self) -> bool:
        now = self.clock()
        self.tokens = min(self.burst, self.tokens + (now - self.last) * self.qps)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class FlowControlRecorder:
    """Rate-limits ``record_event(obj, etype, reason, message)`` per object."""

    def __init__(self, cluster: Any, qps: float = 1.0, burst: int = 5,
                 clock: Callable[[], float] = time.monotonic):
        self.cluster = cluster
        self.qps = qps
        self.burst = burst
        self.clock = clock
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self.dropped = 0

    def _key(self, obj: Any) -> str:
        meta = getattr(obj, "metadata", None)
        uid = getattr(meta, "uid", None) if meta is not None else None
        if uid:
            return str(uid)
        if meta is not None:
            return f"{getattr(meta, 'namespace', '')}/{getattr(meta, 'name', '')}"
        return repr(obj)

    def record_event(self, obj: Any, etype: str, reason: str,
                     message: str) -> bool:
        """True if emitted, False if rate-limited away."""
        key = self._key(obj)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = TokenBucket(
                    self.qps, self.burst, self.clock)
            allowed = bucket.allow()
            if not allowed:
                self.dropped += 1
        if allowed:
            self.cluster.record_event(obj, etype, reason, message)
        return allowed
