"""Shared utilities (L4 analog of the reference's ``pkg/utils``)."""
