"""The regression corpus: minimized failing scenarios checked into
``tests/fuzz_corpus/`` as JSON entries that tier-1 replays forever.

An entry pins three things: the minimized `Scenario` (serialized via
`sim/scenario.scenario_to_doc`), the oracle verdict the fuzz campaign
observed (the failure kinds plus their human-readable details), and
provenance (which preset it grew from, the campaign seed, the mutators
applied, the shrink passes accepted) so a red replay is diagnosable
without re-running the campaign.

``status`` carries the corpus workflow:

* ``known_weakness`` — the bug is real and unfixed; the tier-1 replay
  asserts the oracle STILL reports exactly the pinned kinds (the entry
  is an executable bug report, and a silent behavior change in either
  direction is a finding);
* ``regression_guard`` — the bug was fixed; the replay asserts the
  oracle is clean. Flipping a fixed entry's status (and clearing its
  pinned kinds) is the whole fix-verification ceremony.

`replay` also re-asserts the twin's replayability: the entry's
scenario runs twice into fresh directories and every artifact must
byte-compare equal — a corpus entry that cannot replay byte-identically
cannot pin anything.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpu_on_k8s.sim.fuzz.oracle import OracleConfig, Verdict, run_and_judge
from tpu_on_k8s.sim.scenario import (Scenario, scenario_from_doc,
                                     scenario_to_doc)
from tpu_on_k8s.sim.twin import (LEDGER_FILE, SLO_FILE, SUMMARY_FILE,
                                 TRACE_FILE)

CORPUS_FORMAT = "tpu-on-k8s-fuzz/v1"
STATUS_WEAKNESS = "known_weakness"
STATUS_GUARD = "regression_guard"
ARTIFACTS = (TRACE_FILE, LEDGER_FILE, SLO_FILE, SUMMARY_FILE)


def entry_name(base: str, kinds: Sequence[str],
               scenario_doc: Dict[str, Any]) -> str:
    """Stable, content-derived entry id: base preset, primary failure
    kind, and an 8-hex digest of the canonical scenario doc."""
    blob = json.dumps(scenario_doc, sort_keys=True,
                      separators=(",", ":")).encode()
    digest = hashlib.sha256(blob).hexdigest()[:8]
    primary = (kinds[0] if kinds else "clean").replace(":", "_")
    return f"{base}-{primary}-{digest}"


def make_entry(scenario: Scenario, verdict: Verdict, *, base: str,
               fuzz_seed: int, mutations: Sequence[str] = (),
               shrink_steps: Sequence[str] = (), evals: int = 0,
               status: str = STATUS_WEAKNESS,
               artifacts_sha256: Optional[Dict[str, str]] = None
               ) -> Dict[str, Any]:
    if status not in (STATUS_WEAKNESS, STATUS_GUARD):
        raise ValueError(f"unknown corpus status {status!r}")
    sdoc = scenario_to_doc(scenario)
    entry: Dict[str, Any] = {
        "format": CORPUS_FORMAT,
        "name": entry_name(base, verdict.kinds, sdoc),
        "status": status,
        "scenario": sdoc,
        "oracle": {
            "kinds": list(verdict.kinds),
            "failures": [{"kind": f.kind, "detail": f.detail}
                         for f in verdict.failures],
        },
        "provenance": {
            "base": base,
            "fuzz_seed": fuzz_seed,
            "mutations": list(mutations),
            "shrink_steps": list(shrink_steps),
            "evals": evals,
        },
    }
    if artifacts_sha256:
        entry["artifacts_sha256"] = dict(sorted(artifacts_sha256.items()))
    return entry


def write_entry(corpus_dir: str, entry: Dict[str, Any]) -> str:
    os.makedirs(corpus_dir, exist_ok=True)
    path = os.path.join(corpus_dir, f"{entry['name']}.json")
    with open(path, "w") as f:
        json.dump(entry, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_entries(corpus_dir: str) -> List[Tuple[str, Dict[str, Any]]]:
    """Every ``*.json`` entry under ``corpus_dir``, sorted by filename.
    A file that is not a corpus entry is an error — the corpus
    directory is not a scratch space."""
    out = []
    if not os.path.isdir(corpus_dir):
        return out
    for fname in sorted(os.listdir(corpus_dir)):
        if not fname.endswith(".json"):
            continue
        path = os.path.join(corpus_dir, fname)
        with open(path) as f:
            doc = json.load(f)
        if doc.get("format") != CORPUS_FORMAT:
            raise ValueError(f"{path}: not a fuzz corpus entry "
                             f"(format={doc.get('format')!r})")
        out.append((path, doc))
    return out


def artifact_hashes(outdir: str) -> Dict[str, str]:
    out = {}
    for fname in ARTIFACTS:
        path = os.path.join(outdir, fname)
        if os.path.exists(path):
            with open(path, "rb") as f:
                out[fname] = hashlib.sha256(f.read()).hexdigest()
    return out


@dataclasses.dataclass(frozen=True)
class ReplayResult:
    """One entry replayed twice. ``ok`` folds the three assertions:
    bytes identical across the two runs, and the observed kinds match
    the entry's contract for its status."""

    name: str
    status: str
    pinned_kinds: Tuple[str, ...]
    observed_kinds: Tuple[str, ...]
    byte_identical: bool
    artifacts_sha256: Dict[str, str]
    details: Tuple[str, ...]

    @property
    def kinds_match(self) -> bool:
        if self.status == STATUS_GUARD:
            return not self.observed_kinds
        return self.observed_kinds == self.pinned_kinds

    @property
    def ok(self) -> bool:
        return self.byte_identical and self.kinds_match


def replay(entry: Dict[str, Any],
           cfg: Optional[OracleConfig] = None) -> ReplayResult:
    """Run the entry's scenario twice, byte-compare every artifact,
    and judge the first run against the pinned verdict."""
    sc = scenario_from_doc(entry["scenario"])
    pinned = tuple(entry.get("oracle", {}).get("kinds", ()))
    tmp = tempfile.mkdtemp(prefix="tpu_on_k8s_fuzz_replay_")
    details: List[str] = []
    try:
        dir_a = os.path.join(tmp, "a")
        dir_b = os.path.join(tmp, "b")
        verdict, _ = run_and_judge(sc, cfg, outdir=dir_a)
        run_and_judge(sc, cfg, outdir=dir_b)
        sha_a = artifact_hashes(dir_a)
        sha_b = artifact_hashes(dir_b)
        identical = sha_a == sha_b and set(sha_a) == set(ARTIFACTS)
        if not identical:
            diff = sorted(f for f in set(sha_a) | set(sha_b)
                          if sha_a.get(f) != sha_b.get(f))
            details.append("artifacts differ across replays: "
                           + ", ".join(diff))
        for f in verdict.failures:
            details.append(f"{f.kind}: {f.detail}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return ReplayResult(
        name=str(entry.get("name", "?")),
        status=str(entry.get("status", STATUS_WEAKNESS)),
        pinned_kinds=pinned,
        observed_kinds=verdict.kinds,
        byte_identical=identical,
        artifacts_sha256=sha_a,
        details=tuple(details))
