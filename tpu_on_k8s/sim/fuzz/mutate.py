"""The mutation engine: seeded perturbations over every `Scenario`
knob.

Each mutator is a named pure function ``(rng, scenario, config) ->
Scenario | None`` — ``None`` means "not applicable here" (e.g. you
cannot drop a burst from a burstless profile). `mutate` draws the
mutator *names* and every random number from one caller-owned
``random.Random``, so a (base, seed) pair always produces the same
mutant: the whole fuzz campaign replays from its seed.

Mutations are CLAMPED, not open-ended: amplitudes stay in the DSL's
legal [0,1], durations stay inside ``[min_virtual_s, max_virtual_s]``
(an unbounded fuzzer that doubles `million_diurnal` twice would spend
its whole budget inside one scenario), and cost-model constants stay
inside the calibrated bounds (`sim/calibrate.CostBounds`) — a twin
whose decode step costs a virtual hour finds nothing real.
"""
from __future__ import annotations

import dataclasses
import random
from typing import Callable, List, Optional, Tuple

from tpu_on_k8s.sim.calibrate import CostBounds
from tpu_on_k8s.sim.scenario import (CHAOS_REPLICA_PREEMPT,
                                     CHAOS_SIGNAL_OUTAGE, ChaosWindow,
                                     Scenario)
from tpu_on_k8s.sim.traffic import DiurnalProfile, TenantMix


@dataclasses.dataclass(frozen=True)
class MutationConfig:
    """The fuzzer's guard rails (see module doc)."""

    min_virtual_s: float = 60.0
    max_virtual_s: float = 3600.0
    max_base_rate: float = 64.0
    max_replica_band: int = 16
    #: bounds for cost-constant mutations; None derives symmetric
    #: bounds around the scenario's own cost model (spread 0.5)
    cost_bounds: Optional[CostBounds] = None
    cost_spread: float = 0.5


Mutator = Callable[[random.Random, Scenario, MutationConfig],
                   Optional[Scenario]]


def _clamp(v: float, lo: float, hi: float) -> float:
    return min(max(v, lo), hi)


def _rep(sc: Scenario, **kw) -> Scenario:
    return dataclasses.replace(sc, **kw)


def _rep_profile(sc: Scenario, **kw) -> Scenario:
    return _rep(sc, profile=dataclasses.replace(sc.profile, **kw))


# -------------------------------------------------------------- traffic
def _m_amplitude(rng, sc, cfg):
    a = _clamp(sc.profile.amplitude + rng.uniform(-0.3, 0.3), 0.0, 1.0)
    return _rep_profile(sc, amplitude=round(a, 4))


def _m_phase(rng, sc, cfg):
    p = sc.profile
    shift = rng.uniform(-0.25, 0.25) * p.period_s
    return _rep_profile(sc, peak_at_s=round((p.peak_at_s + shift)
                                            % p.period_s, 3))


def _m_base_rate(rng, sc, cfg):
    mult = rng.choice((0.5, 0.75, 1.25, 1.5, 2.0))
    r = _clamp(sc.profile.base_rate * mult, 0.5, cfg.max_base_rate)
    return _rep_profile(sc, base_rate=round(r, 4))


def _m_burst_add(rng, sc, cfg):
    d = sc.duration_s
    start = round(rng.uniform(0.0, 0.8 * d), 3)
    length = round(rng.uniform(0.05, 0.3) * d, 3)
    mult = round(rng.uniform(2.0, 10.0), 3)
    bursts = sc.profile.bursts + ((start, length, mult),)
    return _rep_profile(sc, bursts=bursts)


def _m_burst_drop(rng, sc, cfg):
    if not sc.profile.bursts:
        return None
    i = rng.randrange(len(sc.profile.bursts))
    bursts = (sc.profile.bursts[:i] + sc.profile.bursts[i + 1:])
    return _rep_profile(sc, bursts=bursts)


def _m_burst_shift(rng, sc, cfg):
    if not sc.profile.bursts:
        return None
    i = rng.randrange(len(sc.profile.bursts))
    start, length, mult = sc.profile.bursts[i]
    start = round(_clamp(start + rng.uniform(-0.2, 0.2) * sc.duration_s,
                         0.0, 0.9 * sc.duration_s), 3)
    bursts = (sc.profile.bursts[:i] + ((start, length, mult),)
              + sc.profile.bursts[i + 1:])
    return _rep_profile(sc, bursts=bursts)


def _m_burst_scale(rng, sc, cfg):
    if not sc.profile.bursts:
        return None
    i = rng.randrange(len(sc.profile.bursts))
    start, length, mult = sc.profile.bursts[i]
    mult = round(_clamp(mult * rng.choice((0.5, 1.5, 2.0)), 1.1, 12.0), 3)
    length = round(_clamp(length * rng.choice((0.5, 1.0, 1.5)),
                          1.0, sc.duration_s), 3)
    bursts = (sc.profile.bursts[:i] + ((start, length, mult),)
              + sc.profile.bursts[i + 1:])
    return _rep_profile(sc, bursts=bursts)


def _m_duration(rng, sc, cfg):
    d = _clamp(sc.duration_s * rng.choice((0.5, 0.75, 1.5)),
               cfg.min_virtual_s, cfg.max_virtual_s)
    return _rep(sc, duration_s=round(d, 3))


def _m_tenants(rng, sc, cfg):
    t = sc.tenants
    weights = tuple(round(rng.uniform(0.5, 4.0), 3) for _ in t.names)
    return _rep(sc, tenants=TenantMix(names=t.names, weights=weights))


def _m_request_shape(rng, sc, cfg):
    lo = rng.randrange(2, 16)
    hi = lo + rng.randrange(4, 32)
    if rng.random() < 0.5:
        return _rep(sc, prompt_lens=(lo, hi))
    return _rep(sc, new_tokens=(lo, hi))


# ---------------------------------------------------------------- models
def _m_models(rng, sc, cfg):
    if sc.n_models <= 0:
        return None
    n = max(1, min(64, sc.n_models + rng.choice((-16, -8, 8, 16))))
    s = round(_clamp(sc.model_zipf_s + rng.uniform(-0.2, 0.4),
                     0.8, 1.8), 4)
    return _rep(sc, n_models=n, model_zipf_s=s)


# ----------------------------------------------------------------- chaos
def _m_chaos_add_outage(rng, sc, cfg):
    at = round(rng.uniform(0.0, 0.9 * sc.duration_s), 3)
    dur = round(rng.uniform(sc.scrape_period_s, 60.0), 3)
    w = ChaosWindow(at_s=at, kind=CHAOS_SIGNAL_OUTAGE, duration_s=dur,
                    note="fuzz:outage")
    return _rep(sc, chaos=sc.chaos + (w,))


def _m_chaos_add_preempt(rng, sc, cfg):
    at = round(rng.uniform(0.0, 0.9 * sc.duration_s), 3)
    w = ChaosWindow(at_s=at, kind=CHAOS_REPLICA_PREEMPT,
                    note="fuzz:preempt")
    return _rep(sc, chaos=sc.chaos + (w,))


def _m_chaos_shift(rng, sc, cfg):
    if not sc.chaos:
        return None
    i = rng.randrange(len(sc.chaos))
    w = sc.chaos[i]
    at = round(_clamp(w.at_s + rng.uniform(-0.2, 0.2) * sc.duration_s,
                      0.0, 0.95 * sc.duration_s), 3)
    moved = ChaosWindow(at_s=at, kind=w.kind, duration_s=w.duration_s,
                        note=w.note)
    return _rep(sc, chaos=sc.chaos[:i] + (moved,) + sc.chaos[i + 1:])


def _m_chaos_drop(rng, sc, cfg):
    if not sc.chaos:
        return None
    i = rng.randrange(len(sc.chaos))
    return _rep(sc, chaos=sc.chaos[:i] + sc.chaos[i + 1:])


# --------------------------------------------------------- control plane
def _m_band(rng, sc, cfg):
    if rng.random() < 0.5:
        mx = max(sc.min_replicas,
                 min(cfg.max_replica_band,
                     sc.max_replicas + rng.choice((-2, -1, 1, 2))))
        return _rep(sc, max_replicas=mx)
    mn = max(1, min(sc.max_replicas,
                    sc.min_replicas + rng.choice((-1, 1))))
    return _rep(sc, min_replicas=mn)


def _m_cooldowns(rng, sc, cfg):
    up = round(_clamp(sc.up_cooldown_s * rng.choice((0.25, 0.5, 2.0)),
                      5.0, 1200.0), 3)
    down = round(_clamp(sc.down_cooldown_s * rng.choice((0.25, 0.5, 2.0)),
                        5.0, 2400.0), 3)
    guard = round(_clamp(sc.flap_guard_s * rng.choice((0.25, 0.5, 2.0)),
                         1.0, 600.0), 3)
    return _rep(sc, up_cooldown_s=up, down_cooldown_s=down,
                flap_guard_s=guard)


def _m_slo_window(rng, sc, cfg):
    w = round(_clamp(sc.slo_window_s * rng.choice((0.25, 0.5, 2.0, 4.0)),
                     30.0, 4.0 * sc.duration_s), 3)
    return _rep(sc, slo_window_s=w)


def _m_slo_targets(rng, sc, cfg):
    mult = rng.choice((0.5, 0.75, 1.5))
    target = round(_clamp(sc.target_ttft_s * mult, 0.05, 5.0), 4)
    slo = round(_clamp(sc.slo_ttft_s * mult, target, 6.0), 4)
    return _rep(sc, target_ttft_s=target, slo_ttft_s=slo)


def _m_queue_depth(rng, sc, cfg):
    return _rep(sc, max_queue_depth=rng.choice((50, 200, 1000, 5000,
                                                50_000)))


# ---------------------------------------------------------------- broker
def _m_broker(rng, sc, cfg):
    if sc.broker_capacity_chips <= 0:
        return None
    cap = max(4, min(32, sc.broker_capacity_chips
                     + rng.choice((-4, -2, 2, 4))))
    backlog = max(0, sc.batch_backlog + rng.choice((-200, -100, 100, 200)))
    units = max(0, min(12, sc.batch_max_units + rng.choice((-2, -1, 1, 2))))
    return _rep(sc, broker_capacity_chips=cap, batch_backlog=backlog,
                batch_max_units=units)


# ------------------------------------------------------------ cost model
def _m_cost(rng, sc, cfg):
    bounds = cfg.cost_bounds or CostBounds.around(sc.cost, cfg.cost_spread)
    jig = dataclasses.replace(
        sc.cost,
        step_s=round(sc.cost.step_s * rng.uniform(0.6, 1.6), 6),
        prefill_cost=round(sc.cost.prefill_cost * rng.uniform(0.6, 1.6), 6),
        compile_s=round(sc.cost.compile_s * rng.uniform(0.6, 1.6), 6))
    return _rep(sc, cost=bounds.clamp(jig))


def _m_seed(rng, sc, cfg):
    return _rep(sc, seed=rng.randrange(1, 1_000_000))


#: name -> mutator, in the fixed order the engine draws from. Append
#: only — reordering reshuffles every existing fuzz seed's campaign.
MUTATORS: Tuple[Tuple[str, Mutator], ...] = (
    ("amplitude", _m_amplitude),
    ("phase", _m_phase),
    ("base_rate", _m_base_rate),
    ("burst_add", _m_burst_add),
    ("burst_drop", _m_burst_drop),
    ("burst_shift", _m_burst_shift),
    ("burst_scale", _m_burst_scale),
    ("duration", _m_duration),
    ("tenants", _m_tenants),
    ("request_shape", _m_request_shape),
    ("models", _m_models),
    ("chaos_add_outage", _m_chaos_add_outage),
    ("chaos_add_preempt", _m_chaos_add_preempt),
    ("chaos_shift", _m_chaos_shift),
    ("chaos_drop", _m_chaos_drop),
    ("band", _m_band),
    ("cooldowns", _m_cooldowns),
    ("slo_window", _m_slo_window),
    ("slo_targets", _m_slo_targets),
    ("queue_depth", _m_queue_depth),
    ("broker", _m_broker),
    ("cost", _m_cost),
    ("seed", _m_seed),
)


def mutator_names() -> List[str]:
    return [name for name, _ in MUTATORS]


def mutate(rng: random.Random, base: Scenario, n: int,
           cfg: Optional[MutationConfig] = None
           ) -> Tuple[Scenario, Tuple[str, ...]]:
    """Apply ``n`` randomly drawn applicable mutators to ``base``.
    Returns the mutant and the names applied (in application order).
    A draw whose mutator is inapplicable or produces an invalid
    Scenario is retried (bounded), so the caller always gets at least
    one applied mutation for n >= 1 on any sane base."""
    cfg = cfg or MutationConfig()
    sc = base
    applied: List[str] = []
    attempts = 0
    while len(applied) < n and attempts < 8 * max(n, 1):
        attempts += 1
        i = rng.randrange(len(MUTATORS))
        name, fn = MUTATORS[i]
        try:
            cand = fn(rng, sc, cfg)
        except ValueError:
            cand = None
        if cand is None:
            continue
        # global guard rails, whatever the mutator touched
        if cand.duration_s > cfg.max_virtual_s:
            cand = _rep(cand, duration_s=cfg.max_virtual_s)
        sc = cand
        applied.append(name)
    return sc, tuple(applied)
