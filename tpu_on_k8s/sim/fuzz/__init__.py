"""Scenario fuzzing: seeded adversarial search over the `Scenario`
DSL, run entirely against the digital twin.

The pipeline is four stages, one module each:

* `mutate`  — a seeded mutation engine that perturbs every knob the
  DSL exposes (traffic curve, bursts, tenant/model mixes, chaos
  windows, autoscale band, broker capacity, cost-model constants
  within calibrated bounds);
* `oracle`  — scores one twin run for *genuine* failures: SLO budget
  exhaustion, autoscaler thrash, interactive refusals, zero-silent-loss
  accounting breaks, open-horizon leaks on the decision ledger, and
  production report-gate failures;
* `shrink`  — a delta-debugging minimizer that simplifies a failing
  scenario (drop chaos, drop bursts, shorten, halve traffic) while the
  oracle still fires the same failure kinds;
* `corpus`  — serializes minimized failures into ``tests/fuzz_corpus/``
  entries that replay byte-identically and pin the oracle verdict;
* `search`  — the budgeted driver tying them together.

Everything is deterministic given (bases, seed, budget): the package
lives under ``tpu_on_k8s/sim`` on purpose, so the determinism analyzer
gates it like the twin itself — no wall clock, no ambient entropy.
"""
from tpu_on_k8s.sim.fuzz.corpus import (ARTIFACTS, CORPUS_FORMAT,
                                        STATUS_GUARD, STATUS_WEAKNESS,
                                        entry_name, load_entries,
                                        make_entry, replay, write_entry)
from tpu_on_k8s.sim.fuzz.mutate import (MUTATORS, MutationConfig, mutate,
                                        mutator_names)
from tpu_on_k8s.sim.fuzz.oracle import (FAIL_ACCOUNTING, FAIL_HORIZON,
                                        FAIL_REFUSALS, FAIL_REPORT_PREFIX,
                                        FAIL_SLO_EXHAUSTED, FAIL_THRASH,
                                        Failure, OracleConfig, Verdict,
                                        judge_run, run_and_judge)
from tpu_on_k8s.sim.fuzz.search import FuzzResult, fuzz
from tpu_on_k8s.sim.fuzz.shrink import complexity, shrink

__all__ = [
    "ARTIFACTS", "CORPUS_FORMAT", "STATUS_GUARD", "STATUS_WEAKNESS",
    "entry_name",
    "load_entries", "make_entry", "replay", "write_entry",
    "MUTATORS", "MutationConfig", "mutate", "mutator_names",
    "FAIL_ACCOUNTING", "FAIL_HORIZON", "FAIL_REFUSALS",
    "FAIL_REPORT_PREFIX", "FAIL_SLO_EXHAUSTED", "FAIL_THRASH",
    "Failure", "OracleConfig", "Verdict", "judge_run", "run_and_judge",
    "FuzzResult", "fuzz", "complexity", "shrink",
]
