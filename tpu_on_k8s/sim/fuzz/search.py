"""The budgeted fuzz driver: enumerate bases, mutate, judge, shrink,
emit corpus entries.

The campaign is a pure function of ``(bases, seed, budget)``: every
random draw comes from one ``random.Random(seed)``, candidates are
generated *before* they are evaluated (so a parallel ``map_fn`` — the
``--workers`` path in `tools/fuzz_run.py` — changes wall time, never
results), and results are processed in candidate order.

Budget accounting is total twin evaluations, shrink included: a
campaign with ``budget=24`` runs the twin at most 24 times, however
the work splits between exploration and minimization. Each confirmed
failure also spends one eval capturing the minimized entry's artifact
hashes (the corpus records what bytes a green replay should produce).

Failures de-duplicate by ``(base preset, failure-kind set)``: a
hundred mutants of the same base all tripping the same oracle are one
weakness, and the corpus stays reviewable.
"""
from __future__ import annotations

import dataclasses
import os
import random
import shutil
import tempfile
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from tpu_on_k8s.sim.fuzz import corpus as corpus_mod
from tpu_on_k8s.sim.fuzz.mutate import MutationConfig, mutate
from tpu_on_k8s.sim.fuzz.oracle import (OracleConfig, Verdict,
                                        run_and_judge)
from tpu_on_k8s.sim.fuzz.shrink import shrink
from tpu_on_k8s.sim.scenario import Scenario

MapFn = Callable[[List[Scenario]], List[Verdict]]
LogFn = Callable[[str], None]


@dataclasses.dataclass(frozen=True)
class FuzzResult:
    """One campaign's outcome. ``entries`` are ready-to-write corpus
    docs (`corpus.write_entry`), in discovery order."""

    entries: Tuple[Dict[str, Any], ...]
    seed: int
    budget: int
    evals: int
    candidates: int
    failures_found: int
    dedup_skipped: int

    def to_doc(self) -> Dict[str, Any]:
        return {
            "seed": self.seed, "budget": self.budget,
            "evals": self.evals, "candidates": self.candidates,
            "failures_found": self.failures_found,
            "dedup_skipped": self.dedup_skipped,
            "entries": [e["name"] for e in self.entries],
        }


def _clamp_base(sc: Scenario, mcfg: MutationConfig) -> Scenario:
    if sc.duration_s > mcfg.max_virtual_s:
        return dataclasses.replace(sc, duration_s=mcfg.max_virtual_s)
    return sc


def fuzz(bases: Sequence[Scenario], *, seed: int, budget: int,
         cfg: Optional[OracleConfig] = None,
         mcfg: Optional[MutationConfig] = None,
         gen_size: int = 8, max_mutations: int = 3,
         shrink_budget: int = 32,
         status: str = corpus_mod.STATUS_WEAKNESS,
         map_fn: Optional[MapFn] = None,
         metrics: Optional[Any] = None,
         log: Optional[LogFn] = None) -> FuzzResult:
    """Run one campaign (see module doc). ``map_fn`` evaluates a
    generation of candidate scenarios and must return verdicts in the
    same order; the default is the in-process serial judge."""
    if not bases:
        raise ValueError("fuzz needs at least one base scenario")
    if budget < 1:
        raise ValueError("budget must be >= 1")
    cfg = cfg or OracleConfig()
    mcfg = mcfg or MutationConfig()
    rng = random.Random(seed)
    say: LogFn = log or (lambda _msg: None)

    def judge(sc: Scenario) -> Verdict:
        return run_and_judge(sc, cfg)[0]

    evaluate: MapFn = map_fn or (lambda scs: [judge(s) for s in scs])
    clamped = [_clamp_base(b, mcfg) for b in bases]
    # candidate stream: every base unmutated first (a planted
    # regression preset must be found on eval #1, not by luck), then
    # round-robin mutants
    pending: List[Tuple[Scenario, str, Tuple[str, ...]]] = [
        (b, b.name, ()) for b in clamped]
    entries: List[Dict[str, Any]] = []
    seen: set = set()
    evals = candidates = failures = deduped = 0
    round_i = 0
    while evals < budget:
        while len(pending) < min(gen_size, budget - evals):
            base = clamped[round_i % len(clamped)]
            round_i += 1
            n_mut = rng.randint(1, max_mutations)
            mutant, applied = mutate(rng, base, n_mut, mcfg)
            pending.append((mutant, base.name, applied))
        gen = pending[:max(1, min(gen_size, budget - evals))]
        pending = pending[len(gen):]
        verdicts = evaluate([sc for sc, _, _ in gen])
        evals += len(gen)
        candidates += len(gen)
        if metrics is not None:
            metrics.inc("evals", len(gen))
        for (sc, base_name, applied), verdict in zip(gen, verdicts):
            if not verdict.failing:
                continue
            failures += 1
            if metrics is not None:
                metrics.inc("failures_found")
            sig = (base_name, verdict.kinds)
            if sig in seen:
                deduped += 1
                if metrics is not None:
                    metrics.inc("dedup_skipped")
                continue
            seen.add(sig)
            say(f"fuzz: {base_name} fails "
                f"[{', '.join(verdict.kinds)}] after {evals} evals "
                f"(mutations: {', '.join(applied) or 'none'})")
            shrink_cap = min(shrink_budget, budget - evals)
            if shrink_cap > 0:
                res = shrink(sc, verdict, judge, budget=shrink_cap)
                evals += res.evals
                if metrics is not None and res.evals:
                    metrics.inc("shrink_evals", res.evals)
                min_sc, min_verdict = res.scenario, res.verdict
                steps = res.steps
            else:
                min_sc, min_verdict, steps = sc, verdict, ()
            sha = {}
            if evals < budget:
                tmp = tempfile.mkdtemp(prefix="tpu_on_k8s_fuzz_pin_")
                try:
                    run_and_judge(min_sc, cfg,
                                  outdir=os.path.join(tmp, "pin"))
                    sha = corpus_mod.artifact_hashes(
                        os.path.join(tmp, "pin"))
                finally:
                    shutil.rmtree(tmp, ignore_errors=True)
                evals += 1
                if metrics is not None:
                    metrics.inc("evals")
            entry = corpus_mod.make_entry(
                min_sc, min_verdict, base=base_name, fuzz_seed=seed,
                mutations=applied, shrink_steps=steps, evals=evals,
                status=status, artifacts_sha256=sha)
            entries.append(entry)
            if metrics is not None:
                metrics.inc("corpus_entries")
            say(f"fuzz: minimized to {entry['name']} "
                f"({len(steps)} shrink steps, {evals}/{budget} evals)")
    return FuzzResult(
        entries=tuple(entries), seed=seed, budget=budget, evals=evals,
        candidates=candidates, failures_found=failures,
        dedup_skipped=deduped)
