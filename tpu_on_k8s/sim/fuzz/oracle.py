"""The oracle layer: score one finished twin run for *genuine*
failures.

"Genuine" is the load-bearing word — a fuzzer whose oracle counts any
page or any scale-up as a bug drowns in noise. Every check here maps to
an invariant the repo already holds elsewhere:

* ``slo_budget_exhausted`` — a TTFT error budget (fleet or per-model)
  ends the run in the ``exhausted`` state: the SLO engine's terminal
  verdict, not a transient page.
* ``autoscaler_thrash`` — committed fleet decisions reverse direction
  (up→down→up…) at least ``thrash_reversals`` times inside any
  ``thrash_window_s`` span: the oscillation the flap guard and
  cooldowns exist to prevent.
* ``request_refusals`` — interactive requests rejected at admission
  (``summary["rejected"] > 0``); the serving plane queues, degrades,
  and scales before it ever refuses.
* ``accounting_break`` — zero-silent-loss arithmetic fails:
  ``requests != served + rejected``, the tracer dropped spans, or the
  batch lane lost work units.
* ``open_horizon_leak`` — a committed decision's effect horizon is
  still open ``horizon_grace_s`` after it landed: the why-chain
  machinery lost track of an in-flight effect (decisions committed
  *near the end of the run* are inside the grace window and exempt —
  their compile legitimately outlives the horizon).
* ``report_check:<tool>`` — a production report gate fails on the
  run's artifact set. The gate itself is INJECTED (`report_gate` on
  `OracleConfig`): ``tpu_on_k8s/sim`` must not import the tools that
  audit it, so `tools/fuzz_run.py` supplies the real gate and library
  users may run oracle-only. ``why_report``/``slo_report`` are only
  meaningful on runs that paged, so the gate receives the page count
  and skips them when it is zero.
"""
from __future__ import annotations

import dataclasses
import shutil
import tempfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tpu_on_k8s.obs.ledger import committed
from tpu_on_k8s.sim.scenario import Scenario
from tpu_on_k8s.sim.twin import DigitalTwin

FAIL_SLO_EXHAUSTED = "slo_budget_exhausted"
FAIL_THRASH = "autoscaler_thrash"
FAIL_REFUSALS = "request_refusals"
FAIL_ACCOUNTING = "accounting_break"
FAIL_HORIZON = "open_horizon_leak"
FAIL_REPORT_PREFIX = "report_check"

#: (outdir, pages) -> [(tool_name, exit_code), ...]
ReportGate = Callable[[str, int], Sequence[Tuple[str, int]]]


@dataclasses.dataclass(frozen=True)
class OracleConfig:
    """Failure thresholds. The defaults are tuned so every *passing*
    registered preset judges clean (tests pin that) — tighten them and
    the fuzzer starts reporting the control plane's normal behavior as
    bugs."""

    #: 4, not 3: the million_diurnal acceptance day legitimately makes
    #: three committed reversals riding its steepest diurnal shoulder —
    #: a blessed preset must judge clean at the default thresholds
    thrash_reversals: int = 4
    thrash_window_s: float = 300.0
    #: None derives per scenario: two compiles plus a scrape and a
    #: reconcile period — the longest an honest horizon stays open
    horizon_grace_s: Optional[float] = None
    report_gate: Optional[ReportGate] = None


@dataclasses.dataclass(frozen=True)
class Failure:
    kind: str
    detail: str


@dataclasses.dataclass(frozen=True)
class Verdict:
    """What the oracle concluded about one run. ``kinds`` is the
    sorted, de-duplicated failure-kind tuple — the shrinker preserves
    it and the corpus pins it."""

    kinds: Tuple[str, ...]
    failures: Tuple[Failure, ...]

    @property
    def failing(self) -> bool:
        return bool(self.kinds)

    @staticmethod
    def of(failures: Sequence[Failure]) -> "Verdict":
        kinds = tuple(sorted({f.kind for f in failures}))
        return Verdict(kinds=kinds, failures=tuple(failures))


def _grace_s(sc: Scenario, cfg: OracleConfig) -> float:
    if cfg.horizon_grace_s is not None:
        return cfg.horizon_grace_s
    return (2.0 * sc.cost.compile_s + sc.scrape_period_s
            + sc.reconcile_period_s)


# ------------------------------------------------------------ the checks
def _check_slo(summary: Dict[str, Any], slo_final: Dict[str, str]
               ) -> List[Failure]:
    out = []
    exhausted = sorted(n for n, s in slo_final.items() if s == "exhausted")
    if exhausted:
        out.append(Failure(FAIL_SLO_EXHAUSTED,
                           f"fleet objectives exhausted at end of run: "
                           f"{', '.join(exhausted)}"))
    model_exhausted = (summary.get("models") or {}).get("slo_exhausted")
    if model_exhausted:
        out.append(Failure(FAIL_SLO_EXHAUSTED,
                           f"per-model budgets exhausted: "
                           f"{', '.join(model_exhausted)}"))
    return out


def _check_thrash(records: List[Dict[str, Any]],
                  cfg: OracleConfig) -> List[Failure]:
    by_loop: Dict[str, List[Tuple[float, str]]] = {}
    for r in records:
        if (r.get("kind") == "decision"
                and str(r.get("loop", "")).startswith("fleetautoscaler/")
                and r.get("action") in ("up", "down")
                and committed(str(r.get("commit", "")))):
            by_loop.setdefault(r["loop"], []).append(
                (float(r["t"]), r["action"]))
    out = []
    for loop, moves in sorted(by_loop.items()):
        reversals = [t for (t, a), (_, prev) in
                     zip(moves[1:], moves[:-1]) if a != prev]
        # sliding window: enough direction flips close together?
        for i in range(len(reversals)):
            j = i
            while (j + 1 < len(reversals)
                   and reversals[j + 1] - reversals[i]
                   <= cfg.thrash_window_s):
                j += 1
            n = j - i + 1
            if n >= cfg.thrash_reversals:
                out.append(Failure(
                    FAIL_THRASH,
                    f"{loop}: {n} direction reversals within "
                    f"{cfg.thrash_window_s:g}s "
                    f"(t={reversals[i]:.1f}..{reversals[j]:.1f})"))
                break
    return out


def _check_refusals(summary: Dict[str, Any]) -> List[Failure]:
    rejected = int(summary.get("rejected", 0))
    if rejected > 0:
        return [Failure(FAIL_REFUSALS,
                        f"{rejected} interactive requests refused at "
                        f"admission")]
    return []


def _check_accounting(summary: Dict[str, Any]) -> List[Failure]:
    out = []
    requests = int(summary.get("requests", 0))
    served = int(summary.get("served", 0))
    rejected = int(summary.get("rejected", 0))
    if requests != served + rejected:
        out.append(Failure(FAIL_ACCOUNTING,
                           f"requests={requests} != served={served} + "
                           f"rejected={rejected}"))
    dropped = int(summary.get("spans_dropped", 0))
    if dropped > 0:
        out.append(Failure(FAIL_ACCOUNTING,
                           f"{dropped} trace spans dropped"))
    if summary.get("batch_intact") is False:
        out.append(Failure(FAIL_ACCOUNTING, "batch lane lost work units"))
    return out


def _check_horizons(records: List[Dict[str, Any]], sc: Scenario,
                    cfg: OracleConfig) -> List[Failure]:
    closed = {r.get("decision") for r in records
              if r.get("kind") == "horizon" and r.get("closing")}
    grace = _grace_s(sc, cfg)
    leaks = []
    for r in records:
        if (r.get("kind") == "decision" and r.get("horizon") == "open"
                and r.get("seq") not in closed
                and float(r.get("t", 0.0)) < sc.duration_s - grace):
            leaks.append(r)
    if not leaks:
        return []
    what = ", ".join(f"seq={r['seq']}@t={float(r['t']):.1f}"
                     for r in leaks[:5])
    return [Failure(FAIL_HORIZON,
                    f"{len(leaks)} effect horizons still open "
                    f">{grace:g}s after commit: {what}")]


def _check_reports(outdir: str, pages: int,
                   cfg: OracleConfig) -> List[Failure]:
    if cfg.report_gate is None:
        return []
    out = []
    for tool, rc in cfg.report_gate(outdir, pages):
        if rc != 0:
            out.append(Failure(f"{FAIL_REPORT_PREFIX}:{tool}",
                               f"{tool} exited {rc}"))
    return out


# ------------------------------------------------------------- top level
def judge_run(twin: DigitalTwin, outdir: Optional[str] = None,
              cfg: Optional[OracleConfig] = None) -> Verdict:
    """Judge one *finished* twin (``run()`` returned, and — when report
    gates are armed — ``write(outdir)`` already emitted the artifact
    set there)."""
    cfg = cfg or OracleConfig()
    sc = twin.scenario
    summary = twin.summary
    records = twin.ledger.export()
    svc_slo: Dict[str, str] = {}
    from tpu_on_k8s.api.inference_types import InferenceService
    from tpu_on_k8s.sim.twin import SERVICE_NAME, SERVICE_NS
    service = twin.cluster.get(InferenceService, SERVICE_NS, SERVICE_NAME)
    if service is not None and service.status.slo:
        svc_slo = {name: st.state
                   for name, st in sorted(service.status.slo.items())}
    failures: List[Failure] = []
    failures += _check_slo(summary, svc_slo)
    failures += _check_thrash(records, cfg)
    failures += _check_refusals(summary)
    failures += _check_accounting(summary)
    failures += _check_horizons(records, sc, cfg)
    if outdir is not None:
        failures += _check_reports(outdir, int(summary.get("pages", 0)),
                                   cfg)
    return Verdict.of(failures)


def run_and_judge(scenario: Scenario,
                  cfg: Optional[OracleConfig] = None,
                  outdir: Optional[str] = None
                  ) -> Tuple[Verdict, Dict[str, Any]]:
    """Run one scenario through the twin and judge it. With ``outdir``
    the artifact set is written there (and kept); otherwise a temp dir
    holds it just long enough for the report gates and is removed."""
    cfg = cfg or OracleConfig()
    twin = DigitalTwin(scenario)
    summary = twin.run()
    tmp = None
    out = outdir
    if out is None and cfg.report_gate is not None:
        tmp = tempfile.mkdtemp(prefix="tpu_on_k8s_fuzz_")
        out = tmp
    try:
        if out is not None:
            twin.write(out)
        verdict = judge_run(twin, out, cfg)
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
    return verdict, summary
