"""Delta-debugging shrinker: minimize a failing scenario while the
oracle still fires the same failure kinds.

Classic ddmin splits a flat input list; a `Scenario` is structured, so
the shrinker instead runs an ordered catalog of *simplification
passes* — drop all chaos, drop one chaos window, disable the training
job, disable the broker, disable the model catalog, collapse to one
tenant, drop a burst, halve a burst, halve the duration, halve the
traffic, flatten the diurnal curve. Greedy first-improvement to a
fixed point: take the first candidate that (a) strictly decreases the
`complexity` tuple and (b) still makes the oracle report every kind
the original failure had (a superset is fine — simplification may
surface a second symptom of the same bug, but it must never *lose*
the one being pinned), then restart from the top of the catalog.

The scenario ``seed`` is never touched here: the minimized scenario
must replay the same bytes the shrink run judged.

Termination: every acceptance strictly decreases a tuple whose
components are bounded below, and the eval ``budget`` caps oracle
calls regardless; determinism: the catalog order is fixed, candidates
are generated in deterministic order, and the judge is the
deterministic twin — same failing scenario, same minimum, every time
(tier-1 pins this).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, List, Optional, Tuple

from tpu_on_k8s.sim.fuzz.oracle import Verdict
from tpu_on_k8s.sim.scenario import Scenario
from tpu_on_k8s.sim.traffic import TenantMix

#: minimum duration a shrink step may leave (one autoscale reconcile
#: plus slack — shorter runs cannot express most failures anyway)
MIN_DURATION_S = 30.0

Judge = Callable[[Scenario], Verdict]


def complexity(sc: Scenario) -> Tuple:
    """The strictly-decreasing acceptance metric. Leading component
    counts the moving parts (chaos windows, bursts, subsystems armed,
    tenants); later components order same-part-count scenarios by how
    much virtual work they schedule."""
    parts = (len(sc.chaos) + len(sc.profile.bursts)
             + (1 if sc.n_models > 0 else 0)
             + (1 if sc.broker_capacity_chips > 0 else 0)
             + (1 if sc.train_workers > 0 else 0)
             + len(sc.tenants.names))
    burst_load = round(sum(m * ln for _, ln, m in sc.profile.bursts), 6)
    return (parts,
            round(sc.duration_s, 6),
            round(sc.profile.base_rate * sc.duration_s, 6),
            burst_load,
            round(sc.profile.amplitude, 6),
            sc.n_models,
            sc.train_workers)


def _rep(sc: Scenario, **kw) -> Scenario:
    return dataclasses.replace(sc, **kw)


def _rep_profile(sc: Scenario, **kw) -> Scenario:
    return _rep(sc, profile=dataclasses.replace(sc.profile, **kw))


# ---------------------------------------------------- the pass catalog
def _p_drop_all_chaos(sc: Scenario) -> Iterator[Scenario]:
    if sc.chaos:
        yield _rep(sc, chaos=())


def _p_drop_one_chaos(sc: Scenario) -> Iterator[Scenario]:
    for i in range(len(sc.chaos)):
        yield _rep(sc, chaos=sc.chaos[:i] + sc.chaos[i + 1:])


def _p_disable_training(sc: Scenario) -> Iterator[Scenario]:
    if sc.train_workers > 0:
        yield _rep(sc, train_workers=0)


def _p_disable_broker(sc: Scenario) -> Iterator[Scenario]:
    if sc.broker_capacity_chips > 0:
        yield _rep(sc, broker_capacity_chips=0, batch_backlog=0,
                   batch_max_units=0)


def _p_disable_models(sc: Scenario) -> Iterator[Scenario]:
    if sc.n_models > 0:
        yield _rep(sc, n_models=0, model_slo_ttft_s=0.0,
                   target_swap_s=0.0)


def _p_halve_models(sc: Scenario) -> Iterator[Scenario]:
    if sc.n_models > 1:
        yield _rep(sc, n_models=sc.n_models // 2)


def _p_single_tenant(sc: Scenario) -> Iterator[Scenario]:
    if len(sc.tenants.names) > 1:
        yield _rep(sc, tenants=TenantMix(names=(sc.tenants.names[0],),
                                         weights=(1.0,)))


def _p_drop_one_burst(sc: Scenario) -> Iterator[Scenario]:
    b = sc.profile.bursts
    for i in range(len(b)):
        yield _rep_profile(sc, bursts=b[:i] + b[i + 1:])


def _p_halve_burst(sc: Scenario) -> Iterator[Scenario]:
    b = sc.profile.bursts
    for i, (start, length, mult) in enumerate(b):
        if mult > 2.0:
            shrunk = (start, length, round(max(mult / 2.0, 1.5), 6))
            yield _rep_profile(sc, bursts=b[:i] + (shrunk,) + b[i + 1:])
        if length > 20.0:
            shrunk = (start, round(length / 2.0, 6), mult)
            yield _rep_profile(sc, bursts=b[:i] + (shrunk,) + b[i + 1:])


def _p_halve_duration(sc: Scenario) -> Iterator[Scenario]:
    if sc.duration_s > 2.0 * MIN_DURATION_S:
        yield _rep(sc, duration_s=round(max(sc.duration_s / 2.0,
                                            MIN_DURATION_S), 6))


def _p_halve_rate(sc: Scenario) -> Iterator[Scenario]:
    if sc.profile.base_rate > 1.0:
        yield _rep_profile(sc, base_rate=round(
            max(sc.profile.base_rate / 2.0, 0.5), 6))


def _p_flatten_curve(sc: Scenario) -> Iterator[Scenario]:
    if sc.profile.amplitude > 0.0:
        yield _rep_profile(sc, amplitude=0.0)


#: fixed order, strongest structural simplifications first — append
#: only (reordering changes every pinned minimum)
PASSES: Tuple[Tuple[str, Callable[[Scenario], Iterator[Scenario]]], ...] = (
    ("drop_all_chaos", _p_drop_all_chaos),
    ("drop_one_chaos", _p_drop_one_chaos),
    ("disable_training", _p_disable_training),
    ("disable_broker", _p_disable_broker),
    ("disable_models", _p_disable_models),
    ("single_tenant", _p_single_tenant),
    ("drop_one_burst", _p_drop_one_burst),
    ("halve_burst", _p_halve_burst),
    ("halve_duration", _p_halve_duration),
    ("halve_rate", _p_halve_rate),
    ("flatten_curve", _p_flatten_curve),
    ("halve_models", _p_halve_models),
)


@dataclasses.dataclass(frozen=True)
class ShrinkResult:
    scenario: Scenario
    verdict: Verdict
    evals: int
    steps: Tuple[str, ...]        # accepted pass names, in order


def shrink(scenario: Scenario, verdict: Verdict, judge: Judge,
           budget: int = 64,
           required_kinds: Optional[Tuple[str, ...]] = None
           ) -> ShrinkResult:
    """Minimize ``scenario`` (which ``judge`` scored as ``verdict``)
    until no catalog pass improves it or ``budget`` oracle evaluations
    are spent. ``required_kinds`` defaults to the verdict's kinds."""
    required = set(required_kinds if required_kinds is not None
                   else verdict.kinds)
    if not required:
        raise ValueError("shrink needs a failing verdict")
    cur, cur_verdict = scenario, verdict
    evals = 0
    steps: List[str] = []
    improved = True
    while improved and evals < budget:
        improved = False
        for name, gen in PASSES:
            accepted = False
            for cand in gen(cur):
                if evals >= budget:
                    break
                try:
                    cand_c = complexity(cand)
                except ValueError:
                    continue
                if not cand_c < complexity(cur):
                    continue
                v = judge(cand)
                evals += 1
                if required <= set(v.kinds):
                    cur, cur_verdict = cand, v
                    steps.append(name)
                    accepted = True
                    break
            if accepted:
                improved = True
                break   # restart the catalog from the top
            if evals >= budget:
                break
    return ShrinkResult(scenario=cur, verdict=cur_verdict, evals=evals,
                        steps=tuple(steps))
