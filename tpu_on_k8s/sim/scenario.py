"""Scenario DSL: one frozen description of everything a twin run does.

A `Scenario` composes the traffic phases (`sim/traffic.DiurnalProfile`
curve + burst windows), the virtual hardware (`sim/devices.DeviceCostModel`),
the serving control-plane knobs (autoscale band, SLO objective and its
scaled burn windows — the same ``window/60`` … ``window/4`` ratios
`serve_load --autoscale-slo` uses), the elastic-training side, and a
chaos schedule. Chaos windows are declared in VIRTUAL TIME and compiled
onto the existing `chaos/injector.FaultRule` machinery, which triggers
by site-hit ordinal: the autoscaler fires ``SITE_AUTOSCALE_SIGNAL``
exactly once per service tick, so a window ``[at_s, at_s+duration_s)``
maps to the tick ordinals inside it — no new chaos sites (the
``SITE_REGISTRY`` gate stays untouched), no new trigger semantics.

``replica_preempt`` windows have no production chaos site (the device
layer is the twin's own); the twin schedules `SimFleet.preempt_replica`
directly at ``at_s`` and logs it into the same chaos event list.

Presets: `smoke()` is the seconds-scale tier-1 scenario;
`million_diurnal()` is the 24-virtual-hour ≥1M-request acceptance
scenario `make twin-soak` replays twice and byte-compares.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Tuple

from tpu_on_k8s.chaos import (SITE_AUTOSCALE_SIGNAL, FaultRule,
                              SignalOutage, Trigger)
from tpu_on_k8s.sim.devices import DeviceCostModel
from tpu_on_k8s.sim.traffic import DiurnalProfile, ModelMix, TenantMix

SCENARIO_FORMAT = "tpu-on-k8s-scenario/v1"

CHAOS_SIGNAL_OUTAGE = "signal_outage"
CHAOS_REPLICA_PREEMPT = "replica_preempt"


@dataclasses.dataclass(frozen=True)
class ChaosWindow:
    """One chaos phase, in virtual time. ``kind`` is
    ``signal_outage`` (the autoscaler's scrape goes dark for
    ``duration_s``) or ``replica_preempt`` (the highest-named live
    replica is killed at ``at_s``; duration ignored)."""

    at_s: float
    kind: str = CHAOS_SIGNAL_OUTAGE
    duration_s: float = 0.0
    note: str = ""

    def __post_init__(self):
        if self.kind not in (CHAOS_SIGNAL_OUTAGE, CHAOS_REPLICA_PREEMPT):
            raise ValueError(f"unknown chaos kind {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class Scenario:
    """The whole rehearsal, declaratively. Everything downstream —
    trace, ledger, budget log, summary — is a pure function of this
    object plus its ``seed``."""

    name: str
    seed: int
    duration_s: float
    profile: DiurnalProfile
    tenants: TenantMix = TenantMix()
    prompt_lens: Tuple[int, int] = (4, 24)
    new_tokens: Tuple[int, int] = (4, 16)
    tick_s: float = 1.0
    cost: DeviceCostModel = DeviceCostModel()

    # serving control plane
    min_replicas: int = 2
    max_replicas: int = 8
    min_warm: int = 0
    target_ttft_s: float = 0.5
    slo_ttft_s: float = 0.6
    slo_window_s: float = 600.0
    scrape_period_s: float = 5.0
    reconcile_period_s: float = 15.0
    max_queue_depth: int = 50_000
    max_step: int = 2
    up_cooldown_s: float = 60.0
    down_cooldown_s: float = 600.0
    flap_guard_s: float = 30.0

    # elastic training side (0 workers disables it); the latency plan
    # maps worker count -> the [elastic-metrics] latency the virtual job
    # reports at that size, scripting the grow/grow/regress-freeze story
    train_workers: int = 2
    train_topology: str = "2x4"
    train_max_hosts: int = 8
    train_obs_period_s: float = 30.0
    train_scale_period_s: float = 60.0
    train_latency_plan: Tuple[Tuple[int, float], ...] = (
        (2, 1.0), (4, 0.6), (8, 2.0))

    # chaos
    chaos: Tuple[ChaosWindow, ...] = ()

    # tracer retention knob for the run (1 = keep everything)
    sample_every: int = 1

    # capacity broker + batch lane (0 capacity disables the market and
    # the lane entirely — the presets above stay byte-identical)
    broker_capacity_chips: int = 0
    broker_period_s: float = 10.0
    batch_backlog: int = 0
    batch_max_units: int = 0
    batch_work: int = 2

    # multi-model density (0 models disables: no model column is drawn
    # from the rng, no spec.models, every earlier preset byte-identical).
    # The catalog is zipf-weighted — a few hot models, a long cold tail.
    # ``model_slo_ttft_s`` > 0 gives EVERY catalog model a per-model
    # TTFT objective on the CRD; ``target_swap_s`` > 0 arms the
    # autoscaler's swap-latency cold-start signal.
    n_models: int = 0
    model_zipf_s: float = 1.05
    model_slo_ttft_s: float = 0.0
    target_swap_s: float = 0.0

    def model_mix(self) -> ModelMix:
        """The zipf catalog (call only when ``n_models`` > 0)."""
        return ModelMix.zipf(self.n_models, s=self.model_zipf_s)

    def __post_init__(self):
        if self.duration_s <= 0 or self.tick_s <= 0:
            raise ValueError("duration_s and tick_s must be > 0")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")

    # ---------------------------------------------------------- compilation
    def signal_tick_of(self, at_s: float) -> int:
        """The 1-based ``SITE_AUTOSCALE_SIGNAL`` hit ordinal of the
        service tick at or after virtual time ``at_s`` (ticks fire at
        ``scrape_period_s, 2*scrape_period_s, …``)."""
        return max(1, int(math.ceil(at_s / self.scrape_period_s)))

    def fault_rules(self) -> List[FaultRule]:
        """Compile the ``signal_outage`` windows onto the production
        FaultRule machinery (see module doc for the time→ordinal map)."""
        rules: List[FaultRule] = []
        for w in self.chaos:
            if w.kind != CHAOS_SIGNAL_OUTAGE:
                continue
            first = self.signal_tick_of(w.at_s)
            last = max(first, self.signal_tick_of(w.at_s + w.duration_s) - 1)
            rules.append(FaultRule(
                SITE_AUTOSCALE_SIGNAL,
                Trigger(at=tuple(range(first, last + 1))),
                SignalOutage(),
                note=w.note or f"{self.name}:outage@{w.at_s:g}s"))
        return rules

    def preempt_times(self) -> List[Tuple[float, str]]:
        """(virtual time, note) of every ``replica_preempt`` window."""
        return [(w.at_s, w.note or f"{self.name}:preempt@{w.at_s:g}s")
                for w in self.chaos if w.kind == CHAOS_REPLICA_PREEMPT]


# ---------------------------------------------------------------- presets
# Named registry: soak drivers select a base with --scenario=<name> and
# the fuzzer enumerates these as mutation bases. Registration order is
# definition order, which keeps any "iterate all presets" loop seeded
# deterministically.
PRESETS: Dict[str, Callable[..., Scenario]] = {}


def register_preset(fn: Callable[..., Scenario]) -> Callable[..., Scenario]:
    """Class the function as a named scenario preset (key = its name)."""
    PRESETS[fn.__name__] = fn
    return fn


def preset(name: str, seed: int = None) -> Scenario:
    """Build the named preset, optionally overriding its default seed."""
    try:
        fn = PRESETS[name]
    except KeyError:
        raise ValueError(f"unknown scenario preset {name!r}; "
                         f"known: {', '.join(PRESETS)}") from None
    return fn() if seed is None else fn(seed=seed)


def preset_names() -> List[str]:
    return list(PRESETS)


@register_preset
def smoke(seed: int = 2468) -> Scenario:
    """The tier-1 smoke scenario: ~10 virtual minutes, a few thousand
    requests, one burst that pages the TTFT budget and scales the fleet,
    a mid-burst signal outage, and one replica preemption — every twin
    mechanism exercised in well under a wall second."""
    return Scenario(
        name="smoke",
        seed=seed,
        duration_s=600.0,
        tick_s=0.25,
        profile=DiurnalProfile(base_rate=6.0, amplitude=0.3,
                               period_s=600.0, peak_at_s=300.0,
                               bursts=((180.0, 90.0, 6.0),)),
        cost=DeviceCostModel(step_s=0.05, compile_s=20.0, n_slots=8),
        min_replicas=2, max_replicas=8,
        # window << duration: the burst must SLIDE OUT of the budget
        # window before the run ends, or the budget stays exhausted and
        # the why-chain never closes with burn_recovered
        target_ttft_s=0.5, slo_ttft_s=0.6, slo_window_s=150.0,
        scrape_period_s=5.0, flap_guard_s=20.0,
        train_obs_period_s=20.0, train_scale_period_s=40.0,
        chaos=(ChaosWindow(at_s=120.0, kind=CHAOS_SIGNAL_OUTAGE,
                           duration_s=15.0, note="smoke:scrape-dark"),
               ChaosWindow(at_s=420.0, kind=CHAOS_REPLICA_PREEMPT,
                           note="smoke:preempt")),
    )


@register_preset
def broker_contention(seed: int = 1357) -> Scenario:
    """The capacity-market rehearsal: a 12-chip cluster where everyone
    wants the same slices at once. At rest the market is nearly full —
    serving holds 2, training holds 2, and the broker fills the batch
    lane's 400-item backlog into the remaining idle chips (up to 6
    units). Then the burst pages the TTFT budget (serving demands up to
    8 via urgent scale-ups), the training job's latency plan scripts a
    grow to 4, and the escalation ladder has to arbitrate: degrade
    first, harvest the batch lane within one tick, shrink training
    toward its floor of 2, refuse only when the market is truly dry.
    A mid-burst scrape outage and a replica preemption ride along so
    the ladder clears under chaos too. Every grant/preempt/refusal is
    one ledger record; `make broker-soak` replays this twice and
    byte-compares the artifact set."""
    return Scenario(
        name="broker_contention",
        seed=seed,
        duration_s=600.0,
        tick_s=0.25,
        profile=DiurnalProfile(base_rate=6.0, amplitude=0.3,
                               period_s=600.0, peak_at_s=300.0,
                               bursts=((180.0, 90.0, 6.0),)),
        cost=DeviceCostModel(step_s=0.05, compile_s=20.0, n_slots=8),
        min_replicas=2, max_replicas=8,
        target_ttft_s=0.5, slo_ttft_s=0.6, slo_window_s=150.0,
        scrape_period_s=5.0, flap_guard_s=20.0,
        train_obs_period_s=20.0, train_scale_period_s=40.0,
        chaos=(ChaosWindow(at_s=200.0, kind=CHAOS_SIGNAL_OUTAGE,
                           duration_s=15.0,
                           note="broker:mid-burst-scrape-dark"),
               ChaosWindow(at_s=420.0, kind=CHAOS_REPLICA_PREEMPT,
                           note="broker:preempt")),
        broker_capacity_chips=12,
        broker_period_s=5.0,
        batch_backlog=400,
        batch_max_units=6,
        batch_work=2,
    )


@register_preset
def multi_model_density(seed: int = 7531) -> Scenario:
    """The model-pool rehearsal: 50 small models behind one fleet,
    zipf-weighted traffic (a few hot heads, a long cold tail), and a
    residency cap that forces real swap churn — every cold-tail request
    risks a ``swap_cold_s`` load that evicts the LRU resident, exactly
    the `serve/modelpool.ModelPool` economics. Every model carries a
    per-model TTFT objective (looser than the fleet SLO — the swap tax
    is priced in), the autoscaler's ``target_swap_s`` cold-start signal
    is armed, and a mid-run burst plus a replica preemption stress the
    pool under churn. The acceptance question is density: the warm
    chip floor must come in far under the one-replica-per-model control
    arm (50 models x one 2x2 slice each) while the per-model budgets
    hold. `make multimodel-soak` replays this twice and byte-compares
    the artifact set."""
    return Scenario(
        name="multi_model_density",
        seed=seed,
        duration_s=600.0,
        tick_s=0.25,
        profile=DiurnalProfile(base_rate=8.0, amplitude=0.3,
                               period_s=600.0, peak_at_s=300.0,
                               bursts=((240.0, 90.0, 4.0),)),
        cost=DeviceCostModel(step_s=0.05, compile_s=20.0, n_slots=8,
                             swap_s=0.05, swap_cold_s=0.25,
                             max_resident_models=8),
        min_replicas=3, max_replicas=8,
        target_ttft_s=0.6, slo_ttft_s=0.8, slo_window_s=150.0,
        scrape_period_s=5.0, flap_guard_s=20.0,
        train_workers=0,
        chaos=(ChaosWindow(at_s=420.0, kind=CHAOS_REPLICA_PREEMPT,
                           note="multimodel:preempt"),),
        n_models=50,
        model_zipf_s=1.05,
        model_slo_ttft_s=1.5,
        target_swap_s=0.4,
    )


@register_preset
def million_diurnal(seed: int = 97) -> Scenario:
    """The acceptance scenario: 24 virtual hours, ≥1M requests across
    three tenants on a diurnal curve, two flash-crowd bursts (the
    second one pages the budget and forces an urgent scale-up whose
    burn recovery closes the why-chain), a scrape outage riding the
    first burst, and an afternoon replica preemption. 1-in-64 trace
    sampling keeps the span dump at report scale; breach/chaos traces
    are pinned, so every cited exemplar still resolves."""
    return Scenario(
        name="million_diurnal",
        seed=seed,
        duration_s=86_400.0,
        tick_s=0.25,
        profile=DiurnalProfile(
            base_rate=12.5, amplitude=0.6, period_s=86_400.0,
            peak_at_s=0.6 * 86_400.0,
            bursts=((4.0 * 3600.0, 1200.0, 6.0),
                    (15.0 * 3600.0, 1800.0, 3.0))),
        tenants=TenantMix(names=("tenant-a", "tenant-b", "tenant-c"),
                          weights=(3.0, 2.0, 1.0)),
        cost=DeviceCostModel(step_s=0.05, compile_s=30.0, n_slots=8),
        min_replicas=2, max_replicas=10,
        target_ttft_s=0.5, slo_ttft_s=0.6, slo_window_s=1800.0,
        scrape_period_s=5.0, flap_guard_s=60.0,
        train_obs_period_s=30.0, train_scale_period_s=60.0,
        chaos=(ChaosWindow(at_s=4.0 * 3600.0 + 300.0,
                           kind=CHAOS_SIGNAL_OUTAGE, duration_s=30.0,
                           note="million:burst1-scrape-dark"),
               ChaosWindow(at_s=13.0 * 3600.0,
                           kind=CHAOS_REPLICA_PREEMPT,
                           note="million:afternoon-preempt")),
        sample_every=64,
    )


@register_preset
def slo_regression(seed: int = 6151) -> Scenario:
    """The deliberately planted failing scenario: a pinned replica band
    (min == max, so the autoscaler cannot add capacity) under a long 8x
    flash crowd, with a budget window wider than the run — the TTFT
    budget exhausts and can never recover. The fuzz smoke run keeps this
    base in its enumeration precisely so the oracle always has one
    genuine failure to find, shrink, and pin into the corpus."""
    return Scenario(
        name="slo_regression",
        seed=seed,
        duration_s=240.0,
        tick_s=0.25,
        profile=DiurnalProfile(base_rate=8.0, amplitude=0.2,
                               period_s=240.0, peak_at_s=120.0,
                               bursts=((60.0, 150.0, 8.0),)),
        cost=DeviceCostModel(step_s=0.05, compile_s=20.0, n_slots=8),
        min_replicas=2, max_replicas=2,
        # window >> duration: once the burst exhausts the budget it
        # stays exhausted through the end of the run
        target_ttft_s=0.5, slo_ttft_s=0.6, slo_window_s=600.0,
        scrape_period_s=5.0, flap_guard_s=20.0,
        train_workers=0,
    )


# ---------------------------------------------------------- serialization
# A Scenario is the unit the fuzzer mutates, shrinks, and checks into
# tests/fuzz_corpus/ — so it needs a stable JSON round trip. Docs are
# tolerant of MISSING fields (they take the dataclass default), which
# lets old corpus entries keep replaying after the DSL grows a knob;
# unknown fields are an error (a corpus entry that spells a knob wrong
# must not silently replay a different scenario).

def _plain(v: Any) -> Any:
    """Tuples -> lists, recursively (JSON has no tuple)."""
    if isinstance(v, tuple):
        return [_plain(x) for x in v]
    return v


def _tupled(v: Any) -> Any:
    """Lists -> tuples, recursively (dataclass fields are tuples)."""
    if isinstance(v, list):
        return tuple(_tupled(x) for x in v)
    return v


def _sub_doc(obj: Any) -> Dict[str, Any]:
    return {f.name: _plain(getattr(obj, f.name))
            for f in dataclasses.fields(obj)}


def _sub_from(cls: type, doc: Dict[str, Any]) -> Any:
    known = {f.name for f in dataclasses.fields(cls)}
    bad = sorted(set(doc) - known)
    if bad:
        raise ValueError(f"unknown {cls.__name__} fields {bad}")
    return cls(**{k: _tupled(v) for k, v in doc.items()})


def scenario_to_doc(sc: Scenario) -> Dict[str, Any]:
    """The scenario as a JSON-ready dict (format-stamped)."""
    doc: Dict[str, Any] = {"format": SCENARIO_FORMAT}
    for f in dataclasses.fields(Scenario):
        v = getattr(sc, f.name)
        if f.name in ("profile", "tenants", "cost"):
            doc[f.name] = _sub_doc(v)
        elif f.name == "chaos":
            doc[f.name] = [_sub_doc(w) for w in v]
        else:
            doc[f.name] = _plain(v)
    return doc


def scenario_from_doc(doc: Dict[str, Any]) -> Scenario:
    """Rebuild a Scenario from `scenario_to_doc` output."""
    fmt = doc.get("format")
    if fmt != SCENARIO_FORMAT:
        raise ValueError(f"not a scenario doc (format={fmt!r})")
    fields = {f.name for f in dataclasses.fields(Scenario)}
    bad = sorted(set(doc) - fields - {"format"})
    if bad:
        raise ValueError(f"unknown Scenario fields {bad}")
    kw: Dict[str, Any] = {}
    for name in fields & set(doc):
        v = doc[name]
        if name == "profile":
            kw[name] = _sub_from(DiurnalProfile, v)
        elif name == "tenants":
            kw[name] = _sub_from(TenantMix, v)
        elif name == "cost":
            kw[name] = _sub_from(DeviceCostModel, v)
        elif name == "chaos":
            kw[name] = tuple(_sub_from(ChaosWindow, w) for w in v)
        else:
            kw[name] = _tupled(v)
    return Scenario(**kw)
