"""Virtual device/slice layer: the stand-in for TPU-backed serving
replicas that the twin's control plane cannot tell from the real thing.

`autoscale/signals.FleetScraper` is explicitly duck-typed ("anything
with a ``replicas`` dict of objects carrying ``metrics`` / ``engine`` /
``outstanding`` / ``routable`` / ``state``"), and
`controller/fleetautoscaler._execute` applies committed decisions via
``fleet.scale_to``. `SimFleet` implements exactly that surface — real
`metrics.ServingMetrics` per replica (mirror deques, monotone counts,
exemplars: the scraper's delta reads work unmodified), virtual
everything else.

Cost model (``DeviceCostModel``): the same constants `serve_load`'s
virtual modes price with — decode costs ``step_base`` (= 1.0,
serve_load ``_DISAGG_STEP_BASE``) step-times per new token, prefill
costs ``prefill_cost`` (= 0.05, serve_load ``_DISAGG_PREFILL_COST``)
step-times per prompt position, and a replica spends ``compile_s``
between creation and readiness (program compile + weights load — the
delay that makes scale-up horizons real: ``replicas_ready`` lands
observably later than the patch). VirtualFlow (PAPERS.md) is the
blueprint: decouple the workload from hardware behind a device layer
priced by a calibrated cost model.

Request lifecycle is event-driven (no per-step ticking): dispatch
computes the request's whole timeline — queue wait, prefill end, first
token, finish — from the cost model and schedules ONE completion event.
Preemption invalidates in-flight timelines by generation counter and
replays the requests (the ``replays`` count rides into span attrs, like
the gateway's crash replays).

Determinism: replica names are counter-derived, dispatch scans
insertion-ordered dicts, queues are FIFO deques — same seed, same
event sequence, same bytes.
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from tpu_on_k8s.metrics.metrics import ServingMetrics
from tpu_on_k8s.sim.clock import EventLoop

#: `serve_load` virtual-mode cost constants (its ``_DISAGG_STEP_BASE``
#: and ``_DISAGG_PREFILL_COST``): decode step-times per new token and
#: per padded prefill position respectively
STEP_BASE = 1.0
PREFILL_COST = 0.05

REPLICA_STARTING = "starting"
REPLICA_READY = "ready"
REPLICA_DRAINING = "draining"


class _Phase:
    """Replica lifecycle phase with the ``.value`` shape the scraper
    reads (``getattr(rep.state, "value", ...)``)."""

    __slots__ = ("value",)

    def __init__(self, value: str) -> None:
        self.value = value


class _EngineStub:
    """The slice stand-in: just the slot capacity the scraper sums."""

    __slots__ = ("n_slots",)

    def __init__(self, n_slots: int) -> None:
        self.n_slots = n_slots


@dataclasses.dataclass(frozen=True)
class DeviceCostModel:
    """Latency pricing for one virtual slice. ``step_s`` is the decode
    step wall-time; everything else is priced in step-times by the
    serve_load constants above.

    Multi-model pricing mirrors `serve/modelpool.ModelPool`'s two-tier
    residency: dispatching a request for a model that is RESIDENT but
    not active costs ``swap_s`` (a params-tree pointer replace), one
    that is not resident costs ``swap_cold_s`` (host load + prepare)
    and evicts the LRU resident when the pool is at
    ``max_resident_models``. Both default to 0 with residency unbounded,
    so every single-model scenario prices exactly as before."""

    step_s: float = 0.05
    step_base: float = STEP_BASE
    prefill_cost: float = PREFILL_COST
    compile_s: float = 30.0
    n_slots: int = 8
    swap_s: float = 0.0
    swap_cold_s: float = 0.0
    max_resident_models: int = 0            # 0 = unbounded residency

    def prefill_s(self, prompt_len: int) -> float:
        return self.step_s * self.prefill_cost * prompt_len

    def decode_s(self, new_tokens: int) -> float:
        return self.step_s * self.step_base * new_tokens

    def service_s(self, prompt_len: int, new_tokens: int) -> float:
        return self.prefill_s(prompt_len) + self.decode_s(new_tokens)


class SimRequest:
    """One in-flight virtual request. Timeline fields are filled at
    dispatch; ``gen`` invalidates a scheduled completion after a
    preemption replay (the completion closure captures the generation
    it was scheduled under)."""

    __slots__ = ("rid", "tenant", "prompt_len", "new_tokens", "submit_t",
                 "dispatch_t", "prefill_end_t", "first_token_t",
                 "finish_t", "replica", "replays", "gen", "model")

    def __init__(self, rid: int, tenant: str, prompt_len: int,
                 new_tokens: int, submit_t: float,
                 model: str = "") -> None:
        self.rid = rid
        self.tenant = tenant
        self.model = model
        self.prompt_len = int(prompt_len)
        self.new_tokens = max(int(new_tokens), 1)
        self.submit_t = submit_t
        self.dispatch_t = 0.0
        self.prefill_end_t = 0.0
        self.first_token_t = 0.0
        self.finish_t = 0.0
        self.replica = ""
        self.replays = 0
        self.gen = 0

    @property
    def queue_wait(self) -> float:
        return self.dispatch_t - self.submit_t

    @property
    def ttft(self) -> float:
        return self.first_token_t - self.submit_t


class SimReplica:
    """One virtual serving replica: the scraper-facing attribute set
    plus slot bookkeeping. ``engine`` is None until the compile
    finishes — a starting replica contributes no slot capacity, exactly
    like a real replica whose engine has not come up. ``active_model``
    / ``resident`` mirror the model pool: one active params tree, an
    LRU set of resident ones (insertion-ordered dict, oldest first)."""

    __slots__ = ("name", "cost", "state", "engine", "metrics",
                 "outstanding", "routable", "inflight", "active_model",
                 "resident")

    def __init__(self, name: str, cost: DeviceCostModel) -> None:
        self.name = name
        self.cost = cost
        self.state = _Phase(REPLICA_STARTING)
        self.engine: Optional[_EngineStub] = None
        self.metrics = ServingMetrics()
        self.outstanding = 0
        self.routable = False
        self.inflight: Dict[int, SimRequest] = {}   # rid -> request
        self.active_model = ""
        self.resident: Dict[str, None] = {}         # LRU, oldest first

    @property
    def free_slots(self) -> int:
        if self.engine is None or not self.routable:
            return 0
        return self.engine.n_slots - self.outstanding


class SimFleet:
    """The virtual fleet: FIFO admission queue, deterministic dispatch,
    ``scale_to`` (the autoscaler's apply target), replica preemption.

    ``on_complete(req)`` is the twin's hook, called at each request's
    completion instant (the clock reads the finish time): it mints the
    span tree and returns the trace id to cite as the TTFT exemplar —
    or None to cite nothing (the sampling knob sheds that trace, and an
    exemplar nothing retains must never be emitted)."""

    def __init__(self, loop: EventLoop, *,
                 cost: Optional[DeviceCostModel] = None,
                 replicas: int = 1, max_queue_depth: int = 10_000,
                 on_complete: Optional[
                     Callable[[SimRequest], Optional[int]]] = None) -> None:
        self.loop = loop
        self.cost = cost if cost is not None else DeviceCostModel()
        self.max_queue_depth = max_queue_depth
        self.on_complete = on_complete
        self.replicas: Dict[str, SimReplica] = {}
        self.queue: Deque[SimRequest] = deque()
        self.stats = {"scale_ups": 0, "scale_downs": 0, "preemptions": 0,
                      "model_swaps": 0, "model_loads": 0,
                      "model_evictions": 0}
        self.served = 0
        self.rejected = 0
        self.replayed = 0
        self._next_replica = 0
        self._desired = 0
        for _ in range(replicas):
            self._add_replica(warm=True)

    # ------------------------------------------------------------- capacity
    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def size(self) -> int:
        """Non-draining replica count — what ``scale_to`` targets."""
        return sum(1 for r in self.replicas.values()
                   if r.state.value != REPLICA_DRAINING)

    @property
    def ready_count(self) -> int:
        return sum(1 for r in self.replicas.values() if r.routable)

    def has_live_requests(self) -> bool:
        return bool(self.queue) or any(r.outstanding
                                       for r in self.replicas.values())

    def _add_replica(self, *, warm: bool = False) -> SimReplica:
        name = f"sim-{self._next_replica}"
        self._next_replica += 1
        rep = SimReplica(name, self.cost)
        self.replicas[name] = rep
        self._desired += 1
        if warm:
            self._make_ready(rep)
        else:
            self.loop.after(self.cost.compile_s,
                            lambda: self._make_ready(rep))
        return rep

    def _make_ready(self, rep: SimReplica) -> None:
        if rep.state.value == REPLICA_STARTING:
            rep.state = _Phase(REPLICA_READY)
            rep.engine = _EngineStub(self.cost.n_slots)
            rep.routable = True
            self._dispatch()

    def scale_to(self, target: int) -> None:
        """The autoscaler's in-process apply: grow with cold (compiling)
        replicas, shrink by draining from the newest name down —
        revived drains come first on the way back up, like a real
        rollout reusing still-warm pods."""
        target = max(int(target), 0)
        current = self.size
        if target > current:
            self.stats["scale_ups"] += 1
            draining = sorted(n for n, r in self.replicas.items()
                              if r.state.value == REPLICA_DRAINING)
            for name in draining[:target - current]:
                rep = self.replicas[name]
                rep.state = _Phase(REPLICA_READY)
                rep.routable = True
                self._desired += 1
                current += 1
            while current < target:
                self._add_replica()
                current += 1
            self._dispatch()
        elif target < current:
            self.stats["scale_downs"] += 1
            active = sorted(n for n, r in self.replicas.items()
                            if r.state.value != REPLICA_DRAINING)
            for name in reversed(active[target:]):
                self._drain(self.replicas[name])

    def _drain(self, rep: SimReplica) -> None:
        rep.state = _Phase(REPLICA_DRAINING)
        rep.routable = False
        self._desired -= 1
        if rep.outstanding == 0:
            self.replicas.pop(rep.name, None)

    def preempt_replica(self, name: str) -> int:
        """Kill a replica instantly (chaos/broker preemption): its
        in-flight requests replay through the queue head in rid order;
        their scheduled completions are invalidated by generation.
        Returns the number of replayed requests."""
        rep = self.replicas.pop(name, None)
        if rep is None:
            return 0
        self.stats["preemptions"] += 1
        if rep.state.value != REPLICA_DRAINING:
            self._desired -= 1
        replay = [rep.inflight[rid] for rid in sorted(rep.inflight)]
        for req in reversed(replay):
            req.gen += 1
            req.replays += 1
            req.replica = ""
            self.queue.appendleft(req)
        self.replayed += len(replay)
        rep.inflight.clear()
        rep.outstanding = 0
        rep.routable = False
        self._dispatch()
        return len(replay)

    # -------------------------------------------------------------- serving
    def submit(self, req: SimRequest) -> bool:
        """Admit one request (False = queue full, rejected)."""
        if len(self.queue) >= self.max_queue_depth:
            self.rejected += 1
            return False
        self.queue.append(req)
        self._dispatch()
        return True

    def _pick_replica(self, model: str = "") -> Optional[SimReplica]:
        """Most-free-slots routing, name tie-break — deterministic and
        balancing, the shape the router's least-loaded policy has. With
        a model, affinity ranks first (active model beats resident beats
        cold), the model-key salting the fleet router's ``route_model``
        applies: swaps happen only when no warm replica has room."""
        best: Optional[SimReplica] = None
        best_rank = None
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            if rep.free_slots <= 0:
                continue
            if model and rep.active_model != model:
                affinity = 1 if model in rep.resident else 2
            else:
                affinity = 0
            rank = (affinity, -rep.free_slots)
            if best is None or rank < best_rank:
                best, best_rank = rep, rank
        return best

    def _swap_in(self, rep: SimReplica, model: str) -> float:
        """Price one model activation on ``rep`` and update its
        residency LRU. Returns the swap-in delay: ``swap_s`` when the
        model was already resident (pointer replace), ``swap_cold_s``
        when it had to be loaded — evicting the LRU resident (and, in
        the real pool, surgically flushing its prefixes) when the pool
        is at ``max_resident_models``."""
        cost = self.cost
        warm = model in rep.resident
        delay = cost.swap_s if warm else cost.swap_cold_s
        if warm:
            del rep.resident[model]         # move-to-end: refresh LRU
        else:
            self.stats["model_loads"] += 1
            cap = cost.max_resident_models
            if cap > 0:
                while len(rep.resident) >= cap:
                    victim = next(iter(rep.resident))
                    del rep.resident[victim]
                    self.stats["model_evictions"] += 1
        rep.resident[model] = None
        rep.active_model = model
        self.stats["model_swaps"] += 1
        rep.metrics.observe("swap_seconds", delay)
        return delay

    def _dispatch(self) -> None:
        now = self.loop.clock.t
        while self.queue:
            req = self.queue[0]
            rep = self._pick_replica(req.model)
            if rep is None:
                return
            self.queue.popleft()
            cost = self.cost
            swap = 0.0
            if req.model and rep.active_model != req.model:
                swap = self._swap_in(rep, req.model)
            elif req.model:
                del rep.resident[req.model]  # refresh LRU on every hit
                rep.resident[req.model] = None
            req.dispatch_t = now
            req.prefill_end_t = now + swap + cost.prefill_s(req.prompt_len)
            req.first_token_t = req.prefill_end_t + cost.step_s
            req.finish_t = (req.prefill_end_t
                            + cost.decode_s(req.new_tokens))
            req.replica = rep.name
            rep.outstanding += 1
            rep.inflight[req.rid] = req
            gen = req.gen
            self.loop.at(req.finish_t,
                         lambda r=req, g=gen: self._complete(r, g))

    def _complete(self, req: SimRequest, gen: int) -> None:
        if req.gen != gen:
            return                          # preempted: a replay owns it now
        rep = self.replicas.get(req.replica)
        if rep is None:
            return                          # replica vanished uncleanly
        rep.outstanding -= 1
        rep.inflight.pop(req.rid, None)
        self.served += 1
        exemplar = (self.on_complete(req)
                    if self.on_complete is not None else None)
        m = rep.metrics
        m.observe("queue_wait_seconds", req.queue_wait)
        m.observe("time_to_first_token_seconds", req.ttft,
                  exemplar=exemplar)
        m.observe("time_per_output_token_seconds", self.cost.step_s)
        if rep.state.value == REPLICA_DRAINING and rep.outstanding == 0:
            self.replicas.pop(rep.name, None)
        self._dispatch()
