"""Seeded traffic generators for the digital twin and the load drivers.

Two generators, two scales:

* ``build_workload`` — the original `tools/serve_load.py` generator,
  moved here VERBATIM (serve_load re-imports it) so the twin and the
  load driver share one copy. It materializes per-request prompt token
  arrays and draws from the rng one request at a time — perfect for the
  soak-scale traces (tens to hundreds of requests) every existing
  `make *-soak` target replays byte-identically, too slow at a million
  requests (~14s measured at 1M, dominated by per-request ndarray
  allocation the simulator never reads).
* ``build_diurnal_trace`` — the vectorized million-scale variant: a
  sinusoidal diurnal rate curve times per-tenant weights, Poisson
  counts per tick, and flat numpy columns (prompt *lengths*, not
  tokens — the virtual device layer prices work by length). ~1M
  requests in well under a second.

Both take the seeded ``numpy`` Generator IN — the caller owns
determinism, the trace is a pure function of (seed, parameters).
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class Arrival:
    """One scheduled request of the trace."""

    step: int
    tenant: str
    prompt: np.ndarray
    max_new_tokens: int
    priority: int = 0
    deadline_s: Optional[float] = None


def build_workload(rng: np.random.Generator, n_requests: int, *,
                   rate: float = 2.0,
                   prompt_lens: Sequence[int] = (4, 24),
                   new_tokens: Sequence[int] = (4, 16),
                   tenants: Sequence[str] = ("tenant-a", "tenant-b",
                                             "tenant-c"),
                   vocab_size: int = 256,
                   deadline_s: Optional[float] = None,
                   deadline_fraction: float = 0.0,
                   shared_prefixes: int = 0,
                   shared_prefix_len: int = 0,
                   shared_fraction: float = 0.0,
                   burst_start: int = 0,
                   burst_len: int = 0,
                   burst_rate: float = 0.0) -> List[Arrival]:
    """A reproducible trace: Poisson(``rate``) arrivals per engine step
    (the seeded ``rng`` is passed IN — the caller owns determinism), mixed
    uniform prompt/output lengths, tenants round-tripped through the same
    rng. ``deadline_fraction`` of requests carry ``deadline_s``. With
    ``shared_prefixes`` > 0, ``shared_fraction`` of requests prepend one
    of that many fixed ``shared_prefix_len``-token prefixes (the
    system-prompt shape real traffic has — what the fleet router's prefix
    affinity exists to exploit; fully independent prompts would leave
    that path structurally cold). With ``burst_len`` > 0, steps in
    ``[burst_start, burst_start + burst_len)`` arrive at ``burst_rate``
    instead of ``rate`` — the bursty trace the SLO autoscaler's reactive
    loop is measured against."""
    pool = [rng.integers(0, vocab_size,
                         size=shared_prefix_len).astype(np.int32)
            for _ in range(shared_prefixes)] if shared_prefix_len else []
    arrivals: List[Arrival] = []
    step = 0
    while len(arrivals) < n_requests:
        step_rate = (burst_rate if burst_len > 0
                     and burst_start <= step < burst_start + burst_len
                     else rate)
        for _ in range(min(int(rng.poisson(step_rate)),
                           n_requests - len(arrivals))):
            lp = int(rng.integers(prompt_lens[0], prompt_lens[1] + 1))
            prompt = rng.integers(0, vocab_size, size=lp).astype(np.int32)
            if pool and rng.random() < shared_fraction:
                prompt = np.concatenate(
                    [pool[int(rng.integers(len(pool)))], prompt])
            arrivals.append(Arrival(
                step=step,
                tenant=str(tenants[int(rng.integers(len(tenants)))]),
                prompt=prompt,
                max_new_tokens=int(rng.integers(new_tokens[0],
                                                new_tokens[1] + 1)),
                deadline_s=(deadline_s
                            if deadline_s is not None
                            and rng.random() < deadline_fraction else None)))
        step += 1
    return arrivals


# --------------------------------------------------------- diurnal traffic
@dataclasses.dataclass(frozen=True)
class TenantMix:
    """Named tenants and their relative traffic weights (normalized at
    draw time — ``(2, 1, 1)`` means the first tenant sends half the
    requests)."""

    names: Tuple[str, ...] = ("tenant-a", "tenant-b", "tenant-c")
    weights: Tuple[float, ...] = (1.0, 1.0, 1.0)

    def __post_init__(self):
        if len(self.names) != len(self.weights) or not self.names:
            raise ValueError("TenantMix needs matching non-empty "
                             "names/weights")
        if min(self.weights) < 0 or sum(self.weights) <= 0:
            raise ValueError("TenantMix weights must be >= 0, sum > 0")


@dataclasses.dataclass(frozen=True)
class DiurnalProfile:
    """The day-shaped arrival-rate curve: a cosine with its crest at
    ``peak_at_s``, modulated ``amplitude`` around ``base_rate``, plus
    explicit burst windows (start, length, rate multiplier) layered on
    top — the flash-crowd spikes a smooth curve alone can never give
    the autoscaler to chew on."""

    base_rate: float = 12.5                 # requests/s averaged over a day
    amplitude: float = 0.6                  # 0 = flat, 1 = trough hits zero
    period_s: float = 86_400.0
    peak_at_s: float = 0.6 * 86_400.0       # mid-afternoon crest
    bursts: Tuple[Tuple[float, float, float], ...] = ()

    def __post_init__(self):
        if self.base_rate <= 0 or self.period_s <= 0:
            raise ValueError("base_rate and period_s must be > 0")
        if not 0.0 <= self.amplitude <= 1.0:
            raise ValueError("amplitude must be in [0, 1]")


def diurnal_rate(profile: DiurnalProfile, t: float) -> float:
    """Instantaneous arrival rate (requests/s) at virtual time ``t``."""
    phase = 2.0 * math.pi * (t - profile.peak_at_s) / profile.period_s
    r = profile.base_rate * (1.0 + profile.amplitude * math.cos(phase))
    for start, length, mult in profile.bursts:
        if start <= t < start + length:
            r *= mult
    return max(r, 0.0)


@dataclasses.dataclass(frozen=True)
class ModelMix:
    """Named models and their relative traffic weights — the
    multi-model analogue of `TenantMix`. `zipf` builds the canonical
    long-tail catalog (weight ``1/rank^s``): a handful of hot models
    and a cold tail, the shape that makes one-replica-per-model
    deployments waste chips and model pooling pay."""

    names: Tuple[str, ...]
    weights: Tuple[float, ...]

    def __post_init__(self):
        if len(self.names) != len(self.weights) or not self.names:
            raise ValueError("ModelMix needs matching non-empty "
                             "names/weights")
        if min(self.weights) < 0 or sum(self.weights) <= 0:
            raise ValueError("ModelMix weights must be >= 0, sum > 0")

    @staticmethod
    def zipf(n: int, s: float = 1.05, prefix: str = "model") -> "ModelMix":
        if n <= 0:
            raise ValueError("zipf catalog needs n >= 1")
        return ModelMix(
            names=tuple(f"{prefix}-{i:02d}" for i in range(n)),
            weights=tuple(1.0 / (i + 1) ** s for i in range(n)))


@dataclasses.dataclass(frozen=True)
class ArrivalTrace:
    """A million-scale trace as flat numpy columns, one row per request,
    sorted by tick. Prompt *lengths* only — the simulated device layer
    prices prefill by length and never reads token values, and a million
    per-request ndarrays is exactly the allocation cost this generator
    exists to avoid. ``tick_offsets[i] : tick_offsets[i+1]`` slices the
    rows arriving at tick ``i`` (len = n_ticks + 1). The ``model``
    column exists only for multi-model traces (``models`` passed to the
    builder); single-model traces leave it None and draw nothing extra
    from the rng, so their bytes are unchanged."""

    tick_s: float
    tick: np.ndarray                        # int64 tick index per request
    prompt_len: np.ndarray                  # int32
    new_tokens: np.ndarray                  # int32
    tenant: np.ndarray                      # int16 index into tenant_names
    tenant_names: Tuple[str, ...]
    tick_offsets: np.ndarray                # int64, len n_ticks + 1
    model: Optional[np.ndarray] = None      # int16 index into model_names
    model_names: Tuple[str, ...] = ()

    @property
    def n(self) -> int:
        return int(self.tick.shape[0])

    @property
    def n_ticks(self) -> int:
        return int(self.tick_offsets.shape[0]) - 1

    def rows_for_tick(self, i: int) -> range:
        return range(int(self.tick_offsets[i]),
                     int(self.tick_offsets[i + 1]))

    def tenant_counts(self):
        """{tenant name: request count} — summary/report material."""
        counts = np.bincount(self.tenant, minlength=len(self.tenant_names))
        return {name: int(counts[i])
                for i, name in enumerate(self.tenant_names)}

    def model_of(self, j: int) -> str:
        """Model name of request row ``j`` ('' on single-model traces)."""
        if self.model is None:
            return ""
        return self.model_names[int(self.model[j])]

    def model_counts(self):
        """{model name: request count} ({} on single-model traces)."""
        if self.model is None:
            return {}
        counts = np.bincount(self.model, minlength=len(self.model_names))
        return {name: int(counts[i])
                for i, name in enumerate(self.model_names)}


def build_diurnal_trace(rng: np.random.Generator, *,
                        profile: DiurnalProfile,
                        tenants: TenantMix = TenantMix(),
                        duration_s: float,
                        tick_s: float = 1.0,
                        prompt_lens: Sequence[int] = (4, 24),
                        new_tokens: Sequence[int] = (4, 16),
                        models: Optional[ModelMix] = None) -> ArrivalTrace:
    """The vectorized diurnal trace: per-tick rates off the profile
    curve, one Poisson draw per tick (vectorized), then single vectorized
    uniform draws for every per-request column. Draw order is fixed —
    (counts, prompt_len, new_tokens, tenant[, model]) — so a trace is a
    pure function of (seed, parameters); same seed, same bytes. The
    model column draws LAST and only when ``models`` is given, so every
    pre-existing single-model trace keeps its exact bytes."""
    n_ticks = int(math.ceil(duration_s / tick_s))
    if n_ticks <= 0:
        raise ValueError("duration_s must cover at least one tick")
    times = np.arange(n_ticks, dtype=np.float64) * tick_s
    rates = (profile.base_rate
             * (1.0 + profile.amplitude
                * np.cos(2.0 * np.pi * (times - profile.peak_at_s)
                         / profile.period_s)))
    for start, length, mult in profile.bursts:
        mask = (times >= start) & (times < start + length)
        rates[mask] *= mult
    np.maximum(rates, 0.0, out=rates)
    counts = rng.poisson(rates * tick_s)
    total = int(counts.sum())
    tick = np.repeat(np.arange(n_ticks, dtype=np.int64), counts)
    lp = rng.integers(prompt_lens[0], prompt_lens[1] + 1,
                      size=total).astype(np.int32)
    nt = rng.integers(new_tokens[0], new_tokens[1] + 1,
                      size=total).astype(np.int32)
    w = np.asarray(tenants.weights, dtype=np.float64)
    edges = np.cumsum(w / w.sum())
    tenant = np.searchsorted(edges, rng.random(total),
                             side="right").astype(np.int16)
    np.minimum(tenant, len(tenants.names) - 1, out=tenant)
    model = None
    model_names: Tuple[str, ...] = ()
    if models is not None:
        mw = np.asarray(models.weights, dtype=np.float64)
        medges = np.cumsum(mw / mw.sum())
        model = np.searchsorted(medges, rng.random(total),
                                side="right").astype(np.int16)
        np.minimum(model, len(models.names) - 1, out=model)
        model_names = tuple(models.names)
    offsets = np.zeros(n_ticks + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return ArrivalTrace(tick_s=float(tick_s), tick=tick, prompt_len=lp,
                        new_tokens=nt, tenant=tenant,
                        tenant_names=tuple(tenants.names),
                        tick_offsets=offsets, model=model,
                        model_names=model_names)
