"""The whole-cluster digital twin: every control loop this repo ships,
closed over a virtual device layer, on one discrete-event clock.

One `run_twin` call stands up the REAL control plane — the
`controller/fleetautoscaler.FleetAutoscaler` (scrape → SLO burn →
recommend → patch → apply), the `controller/inferenceservice` reconciler
maintaining the pod shadow of ``spec.replicas``, the `controller/tpujob`
+ `controller/elastic` reconcilers and the
`controller/autoscaler.ElasticAutoscaler` growing a virtual training
job — and closes the loop through `sim/devices.SimFleet`, whose
latencies come from the serve_load cost constants instead of a real
engine. Traffic is a seeded `sim/traffic.build_diurnal_trace`; chaos is
the scenario's windows compiled onto `chaos/injector.FaultRule`s.

The observability surface is PRODUCTION code, not a twin-side imitation:
the same `obs/trace.Tracer` (request span trees minted at completion
via backdated ``at=`` stamps), the same `obs/ledger.DecisionLedger`,
the same budget event log the SLO engine writes. The dumps this module
emits are therefore bit-compatible with `tools/trace_report.py`,
`tools/why_report.py`, and `tools/slo_report.py` — none of them can
tell a rehearsal from a live run, which is the acceptance bar.

Determinism: no wall clock, no unseeded RNG, no unsorted iteration —
every artifact is a pure function of the `Scenario`. Wall time (for the
``speedup`` gauge) is the DRIVER's concern: `tools/twin_soak.py` injects
``time.perf_counter`` through ``wall_clock``; the twin never reads it
itself, so the determinism analyzer's tier-1 gate holds.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_on_k8s import chaos
from tpu_on_k8s.api.core import (Container, ObjectMeta, PodSpec,
                                 PodTemplateSpec)
from tpu_on_k8s.api.inference_types import (AutoscalePolicy,
                                            InferenceService,
                                            InferenceServiceSpec,
                                            ModelRef, SLOObjective,
                                            SLOPolicy)
from tpu_on_k8s.api.types import (ElasticPolicy, TaskSpec, TaskType,
                                  TPUJob, TPUJobSpec, TPUPolicy)
from tpu_on_k8s.client import InMemoryCluster, KubeletSim
from tpu_on_k8s.controller.autoscaler import setup_elastic_autoscaler
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.elastic import ElasticController
from tpu_on_k8s.controller.failover import InMemoryRestarter
from tpu_on_k8s.controller.fleetautoscaler import FleetAutoscaler
from tpu_on_k8s.controller.inferenceservice import (
    setup_inferenceservice_controller)
from tpu_on_k8s.controller.runtime import Manager, Workqueue
from tpu_on_k8s.controller.tpujob import setup_tpujob_controller, submit_job
from tpu_on_k8s.coordinator.broker import CapacityBroker
from tpu_on_k8s.gang.topology import chips_in_topology
from tpu_on_k8s.metrics.metrics import (AutoscaleMetrics, BrokerMetrics,
                                        LedgerMetrics, SimMetrics)
from tpu_on_k8s.obs.ledger import DecisionLedger
from tpu_on_k8s.obs.slo import page_onsets
from tpu_on_k8s.obs.trace import Tracer
from tpu_on_k8s.serve.batchlane import BatchLane
from tpu_on_k8s.sim.clock import EventLoop, SimClock
from tpu_on_k8s.sim.devices import SimFleet, SimRequest
from tpu_on_k8s.sim.scenario import Scenario
from tpu_on_k8s.sim.traffic import build_diurnal_trace

#: must equal `tools/slo_report.SLO_FORMAT` (asserted by tests/test_sim)
SLO_FORMAT = "tpu-on-k8s-slo/v1"

SERVICE_NS = "default"
SERVICE_NAME = "twin"
TRAIN_JOB = "train"

#: the serving fleet's slice shape — one replica owns one of these
REPLICA_TOPOLOGY = "2x2"

#: spans whose request started within this many virtual seconds of a
#: chaos window are pinned through the sampling knob — "chaos-adjacent"
CHAOS_KEEP_MARGIN_S = 30.0

#: canonical artifact names inside a twin output directory (`.gz` trace
#: and ledger exercise the gzip dump path the report loaders accept)
TRACE_FILE = "trace.json.gz"
LEDGER_FILE = "ledger.json.gz"
SLO_FILE = "slo.json"
SUMMARY_FILE = "summary.json"


class DigitalTwin:
    """One rehearsal run. Construct, `run()`, then `write(outdir)` (or
    use the `run_twin` convenience). Separated so tests can poke at the
    live objects (fleet, tracer, ledger) after the loop drains."""

    def __init__(self, scenario: Scenario, *,
                 wall_clock: Optional[Callable[[], float]] = None,
                 max_spans: int = 600_000) -> None:
        self.scenario = scenario
        self.wall_clock = wall_clock
        self.clock = SimClock()
        self.loop = EventLoop(self.clock)
        self.sim_metrics = SimMetrics()
        self.tracer = Tracer(self.clock, max_spans=max_spans,
                             sample_every=scenario.sample_every)
        self.ledger = DecisionLedger(self.clock, metrics=LedgerMetrics())
        self.pages: List[Dict[str, Any]] = []
        self.preempt_log: List[str] = []
        self.rejected = 0
        self._submitted = 0
        self._tick_no = 0
        self._onsets_seen = 0
        self._train_batch = 0
        self._train_frozen = False
        self._svc_key = f"{SERVICE_NS}/{SERVICE_NAME}"
        sc = scenario
        self._peak_replicas = sc.min_replicas
        self.model_served: Dict[str, int] = {}
        self._model_breaches: Dict[str, int] = {}
        self._keep_windows: List[Tuple[float, float]] = [
            (w.at_s - CHAOS_KEEP_MARGIN_S,
             w.at_s + w.duration_s + CHAOS_KEEP_MARGIN_S)
            for w in sc.chaos]
        self._build_cluster()
        self._build_fleet()
        self._build_traffic()
        self._schedule()

    # ------------------------------------------------------------- wiring
    def _build_cluster(self) -> None:
        sc = self.scenario
        self.cluster = InMemoryCluster()
        self.manager = Manager()
        # The capacity market, on the virtual clock: the broker's tick
        # thread is never started — `_broker_tick` drives `run_once`
        # as a scheduled event, so clearing order is deterministic.
        self.broker: Optional[CapacityBroker] = None
        self.broker_metrics: Optional[BrokerMetrics] = None
        self.batch_lane: Optional[BatchLane] = None
        if sc.broker_capacity_chips > 0:
            self.broker_metrics = BrokerMetrics()
            self.broker = CapacityBroker(
                sc.broker_capacity_chips, ledger=self.ledger,
                metrics=self.broker_metrics,
                period_s=sc.broker_period_s)
            if sc.batch_max_units > 0:
                self.batch_lane = BatchLane(
                    max_units=sc.batch_max_units,
                    default_work=sc.batch_work)
                for _ in range(sc.batch_backlog):
                    self.batch_lane.submit()
                self.broker.register(self.batch_lane.name,
                                     self.batch_lane.bid,
                                     apply_fn=self.batch_lane.apply,
                                     managed=True)
        setup_inferenceservice_controller(self.cluster, self.manager,
                                          clock=self.clock)
        elastic = ElasticController(self.cluster,
                                    restarter=InMemoryRestarter())
        # the twin is fully event-driven (every mutation lands as a
        # watch event the same pump drains), so the engine's 30s safety
        # resync is pure reconcile churn at 24 virtual hours — stretch
        # it to once a virtual hour
        job_cfg = JobControllerConfig(sync_period_seconds=3600.0)
        setup_tpujob_controller(self.cluster, self.manager,
                                config=job_cfg,
                                elastic_controller=elastic)
        self.train_scaler = setup_elastic_autoscaler(self.cluster,
                                                     ledger=self.ledger,
                                                     broker=self.broker)
        self.kubelet = KubeletSim(self.cluster)
        # every reconciler workqueue onto the virtual clock (tpujob's
        # default is wall monotonic — delayed requeues would otherwise
        # become due by WALL time, a nondeterminism leak at >1000x)
        for c in self.manager.controllers:
            c.queue = Workqueue(clock=self.clock)

        w = sc.slo_window_s

        def ttft_slo(target: float) -> SLOPolicy:
            return SLOPolicy(objectives=[SLOObjective(
                name="ttft", objective="ttft_p95", target=target,
                window_s=w, fast_short_s=w / 60, fast_long_s=w / 20,
                slow_short_s=w / 12, slow_long_s=w / 4)])
        # the model-pool catalog: every model on the CRD plane, each
        # with its own (looser — the swap tax is priced in) TTFT budget
        models = []
        if sc.n_models > 0:
            per_model = (ttft_slo(sc.model_slo_ttft_s)
                         if sc.model_slo_ttft_s > 0 else None)
            models = [ModelRef(name=m, image="inproc", slo=per_model)
                      for m in sc.model_mix().names]
        self.cluster.create(InferenceService(
            metadata=ObjectMeta(name=SERVICE_NAME),
            spec=InferenceServiceSpec(
                image="inproc", replicas=sc.min_replicas,
                tpu_policy=TPUPolicy(accelerator="tpu-v5-lite-podslice",
                                     topology=REPLICA_TOPOLOGY),
                autoscale=AutoscalePolicy(
                    min_replicas=sc.min_replicas,
                    max_replicas=sc.max_replicas,
                    min_warm=sc.min_warm,
                    target_ttft_s=sc.target_ttft_s,
                    target_swap_s=sc.target_swap_s,
                    hysteresis=0.1, max_step=sc.max_step,
                    scale_up_cooldown_s=sc.up_cooldown_s,
                    scale_down_cooldown_s=sc.down_cooldown_s,
                    flap_guard_s=sc.flap_guard_s),
                slo=ttft_slo(sc.slo_ttft_s),
                models=models)))
        self.autoscaler = FleetAutoscaler(
            self.cluster,
            config=JobControllerConfig(autoscale_window_scrapes=3,
                                       autoscale_stale_scrapes=3),
            metrics=AutoscaleMetrics(), clock=self.clock,
            tracer=self.tracer, ledger=self.ledger, broker=self.broker)

        if sc.train_workers > 0:
            template = PodTemplateSpec(spec=PodSpec(
                containers=[Container(name="tpu", image="inproc")]))
            submit_job(self.cluster, TPUJob(
                metadata=ObjectMeta(name=TRAIN_JOB),
                spec=TPUJobSpec(
                    tasks={TaskType.WORKER: TaskSpec(
                        num_tasks=sc.train_workers, template=template)},
                    elastic_policy=ElasticPolicy(
                        min_replicas=sc.train_workers,
                        max_replicas=sc.train_max_hosts),
                    tpu_policy=TPUPolicy(
                        accelerator="tpu-v5-lite-podslice",
                        topology=sc.train_topology))))

    def _build_fleet(self) -> None:
        sc = self.scenario
        self.fleet = SimFleet(self.loop, cost=sc.cost,
                              replicas=sc.min_replicas,
                              max_queue_depth=sc.max_queue_depth,
                              on_complete=self._mint)
        self.autoscaler.attach_fleet(SERVICE_NS, SERVICE_NAME, self.fleet)

    def _build_traffic(self) -> None:
        sc = self.scenario
        rng = np.random.default_rng(sc.seed)
        self.trace = build_diurnal_trace(
            rng, profile=sc.profile, tenants=sc.tenants,
            duration_s=sc.duration_s, tick_s=sc.tick_s,
            prompt_lens=sc.prompt_lens, new_tokens=sc.new_tokens,
            models=sc.model_mix() if sc.n_models > 0 else None)

    def _schedule(self) -> None:
        sc = self.scenario
        end = sc.duration_s
        self.loop.every(sc.tick_s, self._tick_arrivals, start_at=0.0,
                        until=end - sc.tick_s)
        self.loop.every(sc.scrape_period_s, self._autoscale_tick,
                        start_at=sc.scrape_period_s, until=end)
        self.loop.every(sc.reconcile_period_s, self._pump,
                        start_at=0.0, until=end)
        if sc.train_workers > 0:
            self.loop.every(sc.train_obs_period_s, self._train_emit,
                            start_at=sc.train_obs_period_s, until=end)
            self.loop.every(sc.train_scale_period_s, self._train_tick,
                            start_at=sc.train_scale_period_s, until=end)
        if self.broker is not None:
            self.loop.every(sc.broker_period_s, self._broker_tick,
                            start_at=sc.broker_period_s, until=end)
        for at_s, note in sc.preempt_times():
            self.loop.at(at_s, lambda n=note: self._preempt(n))

    # ----------------------------------------------------- event handlers
    def _tick_arrivals(self) -> None:
        i = self._tick_no
        self._tick_no += 1
        tr = self.trace
        now = self.clock.t
        for j in tr.rows_for_tick(i):
            req = SimRequest(j, tr.tenant_names[tr.tenant[j]],
                             tr.prompt_len[j], tr.new_tokens[j], now,
                             model=tr.model_of(j))
            self._submitted += 1
            if not self.fleet.submit(req):
                self.rejected += 1

    def _pump(self) -> None:
        """One reconcile round: drain every controller queue (items due
        on the virtual clock), let the kubelet run pending pods, drain
        again — the `run_world` cadence of the controller tests, as a
        scheduled event. Pods only ever appear from a reconcile, so an
        idle round (no reconciles ran) has nothing for the kubelet and
        skips the pod list walk entirely."""
        if self.manager.run_until_idle():
            self.kubelet.run_all(SERVICE_NS)
            self.manager.run_until_idle()

    def _autoscale_tick(self) -> None:
        self.autoscaler.run_once()
        self._peak_replicas = max(self._peak_replicas, self.fleet.size)
        lines = self.autoscaler.slo_event_lines().get(self._svc_key, [])
        onsets = page_onsets(lines)
        if len(onsets) > self._onsets_seen:
            for _ in onsets[self._onsets_seen:]:
                self.pages.append({
                    "t": round(self.clock.t, 6),
                    "slo": "ttft",
                    "step": self.loop.events_processed,
                    "exemplars": self._breach_exemplars(),
                })
            self._onsets_seen = len(onsets)

    def _breach_exemplars(self) -> List[List[Any]]:
        """The page's join key: retained breaching (ttft, trace_id)
        exemplars at the moment the budget blew, merged across replicas
        in name order (deterministic), newest 8. Only sampled-in traces
        ever reach the exemplar deques, so every citation resolves."""
        target = self.scenario.slo_ttft_s
        merged: List[List[Any]] = []
        for name in sorted(self.fleet.replicas):
            rep = self.fleet.replicas[name]
            for v, tid in rep.metrics.exemplars[
                    "time_to_first_token_seconds"]:
                if v > target and isinstance(tid, int):
                    merged.append([round(v, 6), tid])
        return merged[-8:]

    def _train_emit(self) -> None:
        """The virtual training job's worker-0 heartbeat: 5 parseable
        ``[elastic-metrics]`` lines per observation window, latency read
        from the scenario's plan for the CURRENT worker count — the
        script that drives grow → grow → regress-and-freeze."""
        job = self.cluster.get(TPUJob, SERVICE_NS, TRAIN_JOB)
        if job is None:
            return
        workers = job.spec.tasks[TaskType.WORKER].num_tasks
        latency = dict(self.scenario.train_latency_plan).get(workers, 1.0)
        name = f"{TRAIN_JOB}-worker-0"
        for _ in range(5):
            self._train_batch += 1
            self.kubelet.log_line(
                SERVICE_NS, name,
                f"[elastic-metrics] epoch=1 batch={self._train_batch} "
                f"latency={latency} accuracy=0.9")

    def _train_tick(self) -> None:
        if self._train_frozen:
            return   # regressed-and-frozen holds for good; stop ticking
        self.train_scaler.run_once()
        job = self.cluster.get(TPUJob, SERVICE_NS, TRAIN_JOB)
        if job is not None:
            es = job.status.elastic_statuses.get(TaskType.WORKER)
            if es is not None and es.continue_scaling is False:
                self._train_frozen = True

    def _broker_tick(self) -> None:
        """One market clearing + one batch-lane pump on the virtual
        clock. The pump runs AFTER the clearing so a harvest lands
        before the lane admits more backlog into the doomed slots —
        the within-one-tick yield the lane promises."""
        self.broker.run_once()
        if self.batch_lane is not None:
            self.batch_lane.step()

    def _preempt(self, note: str) -> None:
        """Device-layer chaos: kill the newest live replica. No
        production chaos site covers the twin's own device layer, so
        this logs through the twin (and the span substrate) rather than
        inventing a `SITE_REGISTRY` row."""
        live = sorted(n for n, r in self.fleet.replicas.items()
                      if r.state.value != "draining")
        if not live:
            return
        name = live[-1]
        replayed = self.fleet.preempt_replica(name)
        self.preempt_log.append(
            f"t={self.clock.t:.6f} replica={name} replayed={replayed} "
            f"note={note}")
        sp = self.tracer.start("chaos.preempt", at=self.clock.t,
                               replica=name, replayed=replayed,
                               note=note)
        sp.finish(at=self.clock.t)

    # ------------------------------------------------------- span minting
    def _mint(self, req: SimRequest) -> Optional[int]:
        """Mint one request's finished span tree at its completion event
        (every boundary backdated from the timeline the device layer
        already computed — shared floats, so `trace_report`'s residual
        check reads exactly 0). Returns the trace id to cite as the
        TTFT exemplar, or None when the sampling knob shed the trace —
        metrics must never cite a span the dump will not contain."""
        if req.model:
            # per-model accounting rides every completion (sampled or
            # not): the CRD-plane SLO engines and the density summary
            # must see the full population, not the retained traces
            self.model_served[req.model] = \
                self.model_served.get(req.model, 0) + 1
            self.autoscaler.observe_model_latency(
                SERVICE_NS, SERVICE_NAME, req.model, "ttft", req.ttft)
            if req.ttft > self.scenario.model_slo_ttft_s > 0:
                self._model_breaches[req.model] = \
                    self._model_breaches.get(req.model, 0) + 1
        t = self.tracer
        root = t.start("request", at=req.submit_t, rid=req.rid,
                       tenant=req.tenant)
        if req.ttft > self.scenario.slo_ttft_s or req.replays \
                or self._chaos_adjacent(req.submit_t):
            t.keep(root)
        elif not t.is_sampled(root.trace_id):
            # shed trace: don't build children the collector will only
            # throw away — at a million requests the phase spans of
            # unsampled traces are the single largest avoidable cost
            root.finish(at=req.finish_t)
            return None
        t.start("queue", parent=root,
                at=req.submit_t).finish(at=req.dispatch_t)
        t.start("prefill", parent=root, at=req.dispatch_t,
                replica=req.replica).finish(at=req.prefill_end_t)
        d = t.start("decode", parent=root, at=req.prefill_end_t,
                    replica=req.replica)
        d.event("first_token", at=req.first_token_t)
        d.finish(at=req.finish_t)
        if req.replays:
            root.set(replays=req.replays)
        root.finish(at=req.finish_t)
        return root.trace_id if t.is_sampled(root.trace_id) else None

    def _chaos_adjacent(self, t: float) -> bool:
        for lo, hi in self._keep_windows:
            if lo <= t <= hi:
                return True
        return False

    # --------------------------------------------------------------- run
    def run(self) -> Dict[str, Any]:
        """Execute the scenario: chaos installed, recurring loops until
        ``duration_s``, then drain the in-flight tail (completions and
        compile-ready events past the horizon). Returns the
        deterministic summary; wall-clock numbers live in `self.perf`
        (separate, so byte-compares never see them)."""
        sc = self.scenario
        w0 = self.wall_clock() if self.wall_clock is not None else None
        self.chaos_events: List[str] = []
        inj = chaos.FaultInjector(sc.fault_rules(), seed=sc.seed,
                                  name=f"twin-{sc.name}")
        with inj:
            self.loop.run(until=sc.duration_s)
            self.loop.run()        # drain: completions, compiles, pumps
            self._pump()           # final reconcile convergence
            self.chaos_events = list(inj.events)
        self.sim_metrics.inc("events_processed",
                             self.loop.events_processed)
        self.sim_metrics.inc("requests_simulated", self._submitted)
        self.sim_metrics.set_gauge("virtual_seconds_simulated",
                                   self.clock.t)
        self.perf: Dict[str, Any] = {}
        if w0 is not None:
            wall = max(self.wall_clock() - w0, 1e-9)
            self.sim_metrics.set_gauge("wall_seconds", wall)
            self.sim_metrics.set_gauge("speedup", self.clock.t / wall)
            self.perf = {"wall_s": round(wall, 3),
                         "speedup": round(self.clock.t / wall, 1)}
        self.summary = self._summarize()
        return self.summary

    def _summarize(self) -> Dict[str, Any]:
        svc = self.cluster.get(InferenceService, SERVICE_NS, SERVICE_NAME)
        out: Dict[str, Any] = {
            "metric": "twin",
            "scenario": self.scenario.name,
            "seed": self.scenario.seed,
            "virtual_s": round(self.clock.t, 6),
            "events": self.loop.events_processed,
            "requests": self._submitted,
            "served": self.fleet.served,
            "rejected": self.rejected,
            "replayed": self.fleet.replayed,
            "preemptions": self.fleet.stats["preemptions"],
            "scale_ups": self.fleet.stats["scale_ups"],
            "scale_downs": self.fleet.stats["scale_downs"],
            "final_replicas": self.fleet.size,
            "final_spec_replicas": svc.spec.replicas,
            "pages": len(self.pages),
            "budget_transitions": len(
                self.autoscaler.slo_event_lines().get(self._svc_key, [])),
            "chaos_events": len(self.chaos_events),
            "preempt_log": list(self.preempt_log),
            "ledger_records": len(self.ledger.records),
            "spans": len(self.tracer.spans),
            "spans_sampled_out": self.tracer.sampled_out,
            "spans_dropped": self.tracer.dropped,
        }
        if self.scenario.train_workers > 0:
            job = self.cluster.get(TPUJob, SERVICE_NS, TRAIN_JOB)
            out["train_final_workers"] = (
                job.spec.tasks[TaskType.WORKER].num_tasks
                if job is not None else 0)
            out["train_frozen"] = self._train_frozen
        if self.broker is not None:
            out["broker_ticks"] = self.broker.tick_count()
            out["broker_decisions"] = len(self.broker.decision_lines())
        if self.batch_lane is not None:
            out["batch"] = self.batch_lane.snapshot()
            out["batch_intact"] = self.batch_lane.intact()
        if self.scenario.n_models > 0:
            out["models"] = self._model_summary(svc)
        return out

    def _model_summary(self, svc) -> Dict[str, Any]:
        """The density verdict: swap churn, per-model SLO final states
        off the CRD plane, and the chip-cost comparison against the
        one-replica-per-model control arm (the deployment shape the
        model pool exists to beat). ``chips`` prices the fleet's
        actual peak; ``control_arm_chips`` prices a dedicated
        ``REPLICA_TOPOLOGY`` slice per catalog model."""
        sc = self.scenario
        chips_per_replica = chips_in_topology(REPLICA_TOPOLOGY)
        slo_states: Dict[str, str] = {}
        if svc is not None:
            for mname, mst in sorted(svc.status.models.items()):
                for oname, ost in sorted(mst.slo.items()):
                    slo_states[f"{mname}/{oname}"] = ost.state
        exhausted = sorted(k for k, s in slo_states.items()
                           if s == "exhausted")
        top = sorted(self.model_served.items(),
                     key=lambda kv: (-kv[1], kv[0]))[:5]
        return {
            "catalog": sc.n_models,
            "served_models": len(self.model_served),
            "swaps": self.fleet.stats["model_swaps"],
            "loads": self.fleet.stats["model_loads"],
            "evictions": self.fleet.stats["model_evictions"],
            "top_served": [[m, n] for m, n in top],
            "slo_engines": len(slo_states),
            "slo_exhausted": exhausted,
            "breaches": sum(self._model_breaches.values()),
            "peak_replicas": self._peak_replicas,
            "chips": self._peak_replicas * chips_per_replica,
            "control_arm_chips": sc.n_models * chips_per_replica,
        }

    # ------------------------------------------------------------- output
    def write(self, outdir: str) -> Dict[str, str]:
        """Emit the artifact set the production reports consume:
        span dump, decision ledger (with the sibling logs `why_report`
        joins against embedded), SLO budget dump, and the deterministic
        summary. Returns the path map."""
        import os
        os.makedirs(outdir, exist_ok=True)
        paths = {k: os.path.join(outdir, v) for k, v in (
            ("trace", TRACE_FILE), ("ledger", LEDGER_FILE),
            ("slo", SLO_FILE), ("summary", SUMMARY_FILE))}
        self.tracer.dump(paths["trace"])
        extra: Dict[str, Any] = {
            "slo_event_log": self.autoscaler.slo_event_lines()}
        if self.chaos_events:
            extra["chaos_events"] = self.chaos_events
        if self.broker is not None:
            extra["broker_decision_log"] = self.broker.decision_lines()
        self.ledger.dump(paths["ledger"], extra=extra)
        svc = self.cluster.get(InferenceService, SERVICE_NS, SERVICE_NAME)
        slo_status = svc.status.slo or {}
        slo_doc = {
            "format": SLO_FORMAT,
            "seed": self.scenario.seed,
            "slo_target_ttft_s": self.scenario.slo_ttft_s,
            "event_log": list(
                self.autoscaler.slo_event_lines().get(self._svc_key, [])),
            "pages": self.pages,
            "final_state": {name: st.state
                            for name, st in sorted(slo_status.items())},
            "budget_remaining": {
                name: round(st.budget_remaining, 6)
                for name, st in sorted(slo_status.items())},
            # relative to the dump's own directory (slo_report resolves
            # it there), so two outdirs' slo.json byte-compare
            "trace_file": TRACE_FILE,
        }
        with open(paths["slo"], "w") as f:
            json.dump(slo_doc, f, sort_keys=True, separators=(",", ":"))
            f.write("\n")
        with open(paths["summary"], "w") as f:
            json.dump(self.summary, f, sort_keys=True, indent=1)
            f.write("\n")
        return paths


def run_twin(scenario: Scenario, outdir: Optional[str] = None, *,
             wall_clock: Optional[Callable[[], float]] = None
             ) -> Dict[str, Any]:
    """Run one scenario end to end. With ``outdir`` the artifact set is
    written there and the summary gains the path map under ``"out"``."""
    twin = DigitalTwin(scenario, wall_clock=wall_clock)
    summary = twin.run()
    if outdir is not None:
        summary = dict(summary, out=twin.write(outdir))
        twin.summary = summary
    if twin.perf:
        summary = dict(summary, perf=twin.perf)
    return summary
