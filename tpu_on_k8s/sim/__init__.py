"""Whole-cluster digital twin: a deterministic cluster-in-a-process
simulator (ROADMAP item 2, second half).

The kernel half landed first: every control loop is a `LoopKernel`
subclass with an injectable clock, one `DecisionLedger`, byte-identical
seeded replays. This package is the other half — the thing those
injection seams exist *for*. It stands a virtual device layer in for
TPU slices (VirtualFlow's decoupling move, PAPERS.md) and drives the
REAL control plane — `FleetAutoscaler`, `ElasticAutoscaler`,
`SLOEngine`, the `tpujob`/`inferenceservice` reconcilers — against
seeded million-request, multi-tenant, diurnal traffic on one shared
virtual clock, at >1000x real time.

Layout (each module's docstring carries its own contract):

* `clock`    — `SimClock` + the discrete-event `EventLoop` that advances
  the clock to the next due event instead of ticking fixed periods (this
  is what buys the >1000x).
* `traffic`  — the seeded generators. `build_workload`/`Arrival` moved
  here verbatim from `tools/serve_load.py` (which re-imports them);
  `build_diurnal_trace` is the vectorized million-scale variant.
* `devices`  — the virtual device/slice layer: per-replica slot
  capacity, compile/prefill/decode latencies priced by the same cost
  model constants `serve_load`'s virtual modes use, preemption.
* `scenario` — the scenario DSL: traffic phases + chaos schedules
  compiled onto the existing `FaultRule` machinery (no new chaos
  sites), plus the seeded presets `make twin-soak` runs.
* `twin`     — the harness wiring InMemoryCluster, reconcilers,
  autoscalers, SLO engines, tracer, and ledger together and emitting
  the SAME dump formats as production, so `trace_report`, `why_report`,
  and `slo_report` run unmodified on twin output.

Determinism contract: everything observable is a pure function of the
scenario seed. Wall-clock only ever enters through the *injected*
``wall_clock`` callable (the `tools/twin_soak.py` driver passes
``time.perf_counter``; the default is "no wall timing") and lands only
in the perf side-channel, never in byte-compared artifacts.
"""
from tpu_on_k8s.sim.clock import EventLoop, SimClock
from tpu_on_k8s.sim.devices import (DeviceCostModel, SimFleet, SimReplica,
                                    SimRequest)
from tpu_on_k8s.sim.scenario import ChaosWindow, Scenario, million_diurnal, smoke
from tpu_on_k8s.sim.traffic import (Arrival, ArrivalTrace, DiurnalProfile,
                                    TenantMix, build_diurnal_trace,
                                    build_workload, diurnal_rate)
from tpu_on_k8s.sim.twin import DigitalTwin, run_twin

__all__ = [
    "Arrival",
    "ArrivalTrace",
    "ChaosWindow",
    "DeviceCostModel",
    "DigitalTwin",
    "DiurnalProfile",
    "EventLoop",
    "Scenario",
    "SimClock",
    "SimFleet",
    "SimReplica",
    "SimRequest",
    "TenantMix",
    "build_diurnal_trace",
    "build_workload",
    "diurnal_rate",
    "million_diurnal",
    "run_twin",
    "smoke",
]
