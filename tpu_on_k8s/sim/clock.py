"""Discrete-event core of the digital twin: a virtual clock plus an
event loop that JUMPS the clock to the next due event instead of
ticking fixed periods.

This is the whole >1000x-real-time trick: a 24-virtual-hour scenario
costs wall time proportional to its EVENT count (~one per request
completion plus the control-loop cadences), not to its 86 400 virtual
seconds. Every component — autoscalers, SLO engine, tracer, ledger,
reconciler workqueues — reads the same `SimClock` through the clock
injection seams PR 15 built, so the twin's artifacts are stamped on one
coherent virtual timeline.

Determinism: the heap orders events by ``(time, insertion sequence)``,
so same-time events fire in the order they were scheduled — no set or
dict iteration, no identity comparison, nothing the process layout can
perturb. The loop never reads wall-clock (the determinism analyzer
holds `tpu_on_k8s/` to that); wall timing is the *driver's* concern
(`tools/twin_soak.py` injects ``time.perf_counter`` into the harness).
"""
from __future__ import annotations

import heapq
from typing import Callable, List, Optional, Tuple


class SimClock:
    """The twin's virtual clock: callable (``clock()`` → seconds, the
    protocol every injectable-clock seam in the repo expects) and
    advanced only by the event loop or an explicit ``advance`` — the
    same shape as `tools/serve_load.py`'s driver clock, importable."""

    __slots__ = ("t",)

    def __init__(self, t0: float = 0.0) -> None:
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(f"clock cannot run backwards (dt={dt})")
        self.t += dt


class EventLoop:
    """A minimal deterministic discrete-event scheduler.

    ``at(t, fn)`` schedules ``fn`` (no arguments — close over state) at
    virtual time ``t``; ``run(until=...)`` pops events in ``(t, seq)``
    order, sets the clock to each event's time, and calls it. Events
    may schedule further events (including at the current instant —
    they run after everything already due, in scheduling order).
    """

    __slots__ = ("clock", "events_processed", "_heap", "_seq")

    def __init__(self, clock: Optional[SimClock] = None) -> None:
        self.clock = clock if clock is not None else SimClock()
        self.events_processed = 0
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = 0

    def at(self, t: float, fn: Callable[[], None]) -> None:
        if t < self.clock.t:
            raise ValueError(
                f"event at t={t} is in the past (now={self.clock.t})")
        heapq.heappush(self._heap, (float(t), self._seq, fn))
        self._seq += 1

    def after(self, dt: float, fn: Callable[[], None]) -> None:
        self.at(self.clock.t + dt, fn)

    def every(self, period: float, fn: Callable[[], None], *,
              start_at: Optional[float] = None,
              until: Optional[float] = None) -> None:
        """A fixed-cadence event chain: ``fn`` at ``start_at`` (default
        one period from now), then every ``period``, stopping once the
        next firing would land past ``until``. The control loops ride
        this — their cadence is part of the scenario, the clock still
        only ever jumps between due instants."""
        if period <= 0:
            raise ValueError(f"period must be > 0, got {period}")
        first = self.clock.t + period if start_at is None else start_at

        def fire() -> None:
            fn()
            nxt = self.clock.t + period
            if until is None or nxt <= until:
                self.at(nxt, fire)

        if until is None or first <= until:
            self.at(first, fire)

    def next_due(self) -> Optional[float]:
        return self._heap[0][0] if self._heap else None

    @property
    def pending(self) -> int:
        return len(self._heap)

    def run(self, until: Optional[float] = None) -> int:
        """Drain due events (all of them, or those at ``t <= until``),
        jumping the clock to each; with ``until`` set the clock lands
        exactly there even if the heap ran dry earlier. Returns the
        number of events processed by this call."""
        n0 = self.events_processed
        heap = self._heap
        while heap:
            t, _, fn = heap[0]
            if until is not None and t > until:
                break
            heapq.heappop(heap)
            if t > self.clock.t:
                self.clock.t = t
            fn()
            self.events_processed += 1
        if until is not None and self.clock.t < until:
            self.clock.t = until
        return self.events_processed - n0
