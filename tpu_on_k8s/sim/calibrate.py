"""Fit `sim/devices.DeviceCostModel` constants from real chip-window
measurements, so the twin prices virtual latency with numbers the
hardware actually produced instead of hand-picked defaults.

The inputs are the measurement documents the bench harness already
emits (``CHIPWINDOW_r05.json`` / ``BENCH_*.json`` schema): a JSON
object whose top-level values are either run metadata or *stage* dicts.
A stage that died carries ``{"error": ...}`` or ``{"rc": <nonzero>}``
and is skipped; a live stage carries measurements in one of three
shapes this module understands:

* a parsed metric row — ``{"metric": ..., "value": ..., "unit": ...}``
  either directly or under ``"parsed"`` (the BENCH_*.json shape).
  Recognized metrics: ``decode_step_s`` / ``decode_step_ms`` (decode
  step wall time), ``prefill_s_per_token`` / ``prefill_ms_per_token``
  (prefill slope), ``compile_s`` / ``compile_ms``;
* sample lists — ``"decode_steps": [s, ...]`` (seconds per decode
  step), ``"compiles": [s, ...]`` (seconds per compile);
* prefill pairs — ``"prefills": [[prompt_len, seconds], ...]``.

Real windows are messy — a doc where every stage timed out (the
checked-in ``CHIPWINDOW_r05.json`` is exactly that) fits *nothing* and
the calibration falls back to the base model, per constant. The fit is
deliberately simple and closed-form, so two runs over the same docs are
bit-identical (the determinism gate covers this module like the rest of
``sim/``):

* ``step_s``  = median of all decode step samples;
* ``prefill_cost`` = least-squares-through-origin slope of prefill
  seconds vs prompt length, divided by the fitted ``step_s`` (the cost
  model prices prefill as ``step_s * prefill_cost * prompt_len``);
* ``compile_s`` = median of all compile samples.

`CostBounds` wraps a calibration (or a bare cost model) into the
per-constant intervals the scenario fuzzer is allowed to wander in —
"cost-model constants within calibrated bounds" means mutations stay
inside ``[value/ (1+spread), value * (1+spread)]``.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tpu_on_k8s.sim.devices import DeviceCostModel

CALIBRATION_FORMAT = "tpu-on-k8s-calibration/v1"

# metric-name -> (target, seconds-per-unit) for parsed metric rows
_METRIC_MAP = {
    "decode_step_s": ("step", 1.0),
    "decode_step_ms": ("step", 1e-3),
    "prefill_s_per_token": ("prefill_slope", 1.0),
    "prefill_ms_per_token": ("prefill_slope", 1e-3),
    "compile_s": ("compile", 1.0),
    "compile_ms": ("compile", 1e-3),
}


@dataclasses.dataclass(frozen=True)
class Measurements:
    """Everything usable pulled out of one or more measurement docs."""

    decode_steps: Tuple[float, ...] = ()
    prefills: Tuple[Tuple[float, float], ...] = ()   # (prompt_len, s)
    prefill_slopes: Tuple[float, ...] = ()           # s per token
    compiles: Tuple[float, ...] = ()

    def merged(self, other: "Measurements") -> "Measurements":
        return Measurements(
            self.decode_steps + other.decode_steps,
            self.prefills + other.prefills,
            self.prefill_slopes + other.prefill_slopes,
            self.compiles + other.compiles)


@dataclasses.dataclass(frozen=True)
class Calibration:
    """The fitted constants plus how much evidence backed each one.
    A constant with zero samples keeps the base model's value and is
    absent from ``fitted``."""

    step_s: float
    prefill_cost: float
    compile_s: float
    n_steps: int = 0
    n_prefills: int = 0
    n_compiles: int = 0

    @property
    def fitted(self) -> List[str]:
        out = []
        if self.n_steps:
            out.append("step_s")
        if self.n_prefills:
            out.append("prefill_cost")
        if self.n_compiles:
            out.append("compile_s")
        return out

    def cost_model(self, base: Optional[DeviceCostModel] = None
                   ) -> DeviceCostModel:
        """The base model with every fitted constant replaced."""
        base = base or DeviceCostModel()
        return dataclasses.replace(
            base, step_s=self.step_s, prefill_cost=self.prefill_cost,
            compile_s=self.compile_s)

    def to_doc(self) -> Dict[str, Any]:
        return {
            "format": CALIBRATION_FORMAT,
            "step_s": self.step_s,
            "prefill_cost": self.prefill_cost,
            "compile_s": self.compile_s,
            "n_steps": self.n_steps,
            "n_prefills": self.n_prefills,
            "n_compiles": self.n_compiles,
            "fitted": self.fitted,
        }


def calibration_from_doc(doc: Dict[str, Any]) -> Calibration:
    fmt = doc.get("format")
    if fmt != CALIBRATION_FORMAT:
        raise ValueError(f"not a calibration doc (format={fmt!r})")
    return Calibration(
        step_s=float(doc["step_s"]),
        prefill_cost=float(doc["prefill_cost"]),
        compile_s=float(doc["compile_s"]),
        n_steps=int(doc.get("n_steps", 0)),
        n_prefills=int(doc.get("n_prefills", 0)),
        n_compiles=int(doc.get("n_compiles", 0)))


# ------------------------------------------------------------ extraction
def _stage_alive(stage: Dict[str, Any]) -> bool:
    if "error" in stage or "err" in stage:
        return False
    rc = stage.get("rc")
    return not (isinstance(rc, int) and rc != 0)


def _floats(v: Any) -> List[float]:
    if not isinstance(v, list):
        return []
    out = []
    for x in v:
        if isinstance(x, (int, float)) and x > 0:
            out.append(float(x))
    return out


def _pairs(v: Any) -> List[Tuple[float, float]]:
    out = []
    if not isinstance(v, list):
        return out
    for row in v:
        if (isinstance(row, (list, tuple)) and len(row) == 2
                and all(isinstance(x, (int, float)) for x in row)
                and row[0] > 0 and row[1] > 0):
            out.append((float(row[0]), float(row[1])))
    return out


def _metric_rows(stage: Dict[str, Any]) -> List[Dict[str, Any]]:
    rows = []
    if isinstance(stage.get("metric"), str):
        rows.append(stage)
    parsed = stage.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("metric"), str):
        rows.append(parsed)
    return rows


def extract_measurements(doc: Dict[str, Any]) -> Measurements:
    """Pull every usable sample out of one measurement doc. Stages that
    errored or exited nonzero contribute nothing; a doc with no live
    stages yields an empty Measurements (not an error — the caller
    decides whether an evidence-free fit is acceptable)."""
    steps: List[float] = []
    prefills: List[Tuple[float, float]] = []
    slopes: List[float] = []
    compiles: List[float] = []
    stages: Iterable[Tuple[str, Any]] = doc.items()
    for key, stage in stages:
        if key == "parsed":
            # the flat BENCH shape: `parsed` is the DOC's metric row,
            # governed by the doc's own rc — handled below, not a stage
            continue
        if not isinstance(stage, dict) or not _stage_alive(stage):
            continue
        steps.extend(_floats(stage.get("decode_steps")))
        compiles.extend(_floats(stage.get("compiles")))
        prefills.extend(_pairs(stage.get("prefills")))
        for row in _metric_rows(stage):
            tgt = _METRIC_MAP.get(row["metric"])
            v = row.get("value")
            if tgt is None or not isinstance(v, (int, float)) or v <= 0:
                continue
            kind, scale = tgt
            if kind == "step":
                steps.append(v * scale)
            elif kind == "prefill_slope":
                slopes.append(v * scale)
            elif kind == "compile":
                compiles.append(v * scale)
    # the doc itself may be one flat stage (BENCH_*.json shape)
    if _stage_alive(doc):
        for row in _metric_rows(doc):
            tgt = _METRIC_MAP.get(row["metric"])
            v = row.get("value")
            if tgt is None or not isinstance(v, (int, float)) or v <= 0:
                continue
            kind, scale = tgt
            if kind == "step":
                steps.append(v * scale)
            elif kind == "prefill_slope":
                slopes.append(v * scale)
            elif kind == "compile":
                compiles.append(v * scale)
    return Measurements(tuple(steps), tuple(prefills), tuple(slopes),
                        tuple(compiles))


# ------------------------------------------------------------------- fit
def _median(xs: Tuple[float, ...]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def fit(measurements: Measurements,
        base: Optional[DeviceCostModel] = None) -> Calibration:
    """Closed-form fit (see module doc). Constants without evidence
    keep the base model's value."""
    base = base or DeviceCostModel()
    m = measurements
    step_s = _median(m.decode_steps) if m.decode_steps else base.step_s
    n_pre = len(m.prefills) + len(m.prefill_slopes)
    if m.prefills:
        # least squares through the origin: slope = sum(l*s) / sum(l^2),
        # pooled with any directly-reported per-token slopes
        num = sum(length * s for length, s in m.prefills)
        den = sum(length * length for length, _ in m.prefills)
        slopes = list(m.prefill_slopes) + [num / den]
        slope = sum(slopes) / len(slopes)
        prefill_cost = slope / step_s
    elif m.prefill_slopes:
        slope = sum(m.prefill_slopes) / len(m.prefill_slopes)
        prefill_cost = slope / step_s
    else:
        prefill_cost = base.prefill_cost
    compile_s = _median(m.compiles) if m.compiles else base.compile_s
    return Calibration(
        step_s=round(step_s, 9), prefill_cost=round(prefill_cost, 9),
        compile_s=round(compile_s, 9), n_steps=len(m.decode_steps),
        n_prefills=n_pre, n_compiles=len(m.compiles))


def fit_files(paths: Iterable[str],
              base: Optional[DeviceCostModel] = None) -> Calibration:
    """Load + merge every doc, then fit. Unreadable / non-JSON files
    are an error; error-laden stages inside a readable doc are not."""
    merged = Measurements()
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{p}: measurement doc must be an object")
        merged = merged.merged(extract_measurements(doc))
    return fit(merged, base)


# ---------------------------------------------------------------- bounds
@dataclasses.dataclass(frozen=True)
class CostBounds:
    """Per-constant intervals a fuzzed cost model must stay inside —
    the "calibrated bounds" of the scenario mutation engine."""

    step_s: Tuple[float, float]
    prefill_cost: Tuple[float, float]
    compile_s: Tuple[float, float]

    @staticmethod
    def around(cost: DeviceCostModel, spread: float = 0.5) -> "CostBounds":
        """Symmetric multiplicative bounds around one cost model."""
        if spread < 0:
            raise ValueError("spread must be >= 0")

        def band(v: float) -> Tuple[float, float]:
            return (v / (1.0 + spread), v * (1.0 + spread))

        return CostBounds(band(cost.step_s), band(cost.prefill_cost),
                          band(cost.compile_s))

    def clamp(self, cost: DeviceCostModel) -> DeviceCostModel:
        def pin(v: float, lo_hi: Tuple[float, float]) -> float:
            return min(max(v, lo_hi[0]), lo_hi[1])

        return dataclasses.replace(
            cost,
            step_s=pin(cost.step_s, self.step_s),
            prefill_cost=pin(cost.prefill_cost, self.prefill_cost),
            compile_s=pin(cost.compile_s, self.compile_s))


# ------------------------------------------------------------------- CLI
def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="fit DeviceCostModel constants from chip-window / "
                    "bench measurement docs")
    p.add_argument("paths", nargs="+", help="CHIPWINDOW_*.json / "
                   "BENCH_*.json measurement documents")
    p.add_argument("--strict", action="store_true",
                   help="exit 3 when no constant could be fitted")
    args = p.parse_args(argv)
    cal = fit_files(args.paths)
    print(json.dumps(cal.to_doc(), indent=1, sort_keys=True))
    if args.strict and not cal.fitted:
        return 3
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
