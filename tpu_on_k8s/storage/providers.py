"""Storage providers: PV creation + pod volume injection per storage flavor.

Analog of /root/reference/pkg/storage/{interface.go,local_storage.go,nfs.go,
registry/registry.go}: the provider is picked by which field of the tagged
``Storage`` union is set (registry.go:36-44). GCS is new — the idiomatic artifact
store for TPU-on-GKE (mounted via GCS FUSE CSI in a real cluster; modeled as a
volume here).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import ObjectMeta, PodSpec, Volume, VolumeMount
from tpu_on_k8s.api.model_types import ModelVersion, Storage


@dataclass
class PersistentVolumeSpec:
    """Flat internal fields; the wire hooks speak real core/v1
    PersistentVolumeSpec — ``capacity: {storage: "NGi"}``, nested
    ``hostPath``/``nfs`` sources, ``claimRef: {namespace, name}``,
    ``nodeAffinity`` for the local pin, and the GCS flavor as the GKE
    GCS-FUSE CSI source (``csi.driver: gcsfuse.csi.storage.gke.io``) — so a
    real apiserver accepts the ModelVersion pipeline's PVs instead of
    pruning them to empty specs."""

    capacity_gi: int = 10
    access_modes: list = field(default_factory=lambda: ["ReadWriteOnce"])
    host_path: Optional[str] = None
    node_name: Optional[str] = None  # node-affinity pin for local storage
    nfs_server: Optional[str] = None
    nfs_path: Optional[str] = None
    gcs_bucket: Optional[str] = None
    gcs_prefix: Optional[str] = None
    claim_ref: str = ""              # "namespace/name" of the bound claim

    _GCS_DRIVER = "gcsfuse.csi.storage.gke.io"

    @staticmethod
    def __wire_out__(d):
        out: dict = {"capacity": {"storage": f"{d.pop('capacityGi', 10)}Gi"}}
        if d.get("accessModes"):
            out["accessModes"] = d["accessModes"]
        if d.get("hostPath"):
            out["hostPath"] = {"path": d["hostPath"]}
        if d.get("nfsServer"):
            out["nfs"] = {"server": d["nfsServer"],
                          "path": d.get("nfsPath") or ""}
        if d.get("gcsBucket"):
            attrs = {}
            if d.get("gcsPrefix"):
                attrs["mountOptions"] = f"only-dir={d['gcsPrefix']}"
            out["csi"] = {"driver": PersistentVolumeSpec._GCS_DRIVER,
                          "volumeHandle": d["gcsBucket"],
                          **({"volumeAttributes": attrs} if attrs else {})}
        if d.get("claimRef"):
            ns, sep, name = d["claimRef"].partition("/")
            if not sep:                      # bare claim name, no namespace
                ns, name = "", ns
            out["claimRef"] = {**({"namespace": ns} if ns else {}),
                               "name": name,
                               "kind": "PersistentVolumeClaim",
                               "apiVersion": "v1"}
        if d.get("nodeName"):
            out["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
                {"matchExpressions": [{"key": "kubernetes.io/hostname",
                                       "operator": "In",
                                       "values": [d["nodeName"]]}]}]}}
        return out

    @staticmethod
    def __wire_in__(d):
        if "capacity" not in d and "claimRef" not in d and \
                "nodeAffinity" not in d and not any(
                    isinstance(d.get(k), dict) for k in ("hostPath", "nfs",
                                                         "csi")):
            return d  # internal snake_case form
        out: dict = {}
        cap = d.get("capacity")
        if isinstance(cap, dict) and cap.get("storage"):
            out["capacity_gi"] = _parse_gi(cap["storage"])
        if d.get("accessModes"):
            out["access_modes"] = d["accessModes"]
        hp = d.get("hostPath")
        if isinstance(hp, dict):
            out["host_path"] = hp.get("path")
        nfs = d.get("nfs")
        if isinstance(nfs, dict):
            out["nfs_server"] = nfs.get("server")
            out["nfs_path"] = nfs.get("path")
        csi = d.get("csi")
        if isinstance(csi, dict) and \
                csi.get("driver") == PersistentVolumeSpec._GCS_DRIVER:
            out["gcs_bucket"] = csi.get("volumeHandle")
            mo = (csi.get("volumeAttributes") or {}).get("mountOptions", "")
            if mo.startswith("only-dir="):
                out["gcs_prefix"] = mo[len("only-dir="):]
        cr = d.get("claimRef")
        if isinstance(cr, dict):
            ns, name = cr.get("namespace", ""), cr.get("name", "")
            out["claim_ref"] = f"{ns}/{name}" if ns else name
        na = d.get("nodeAffinity")
        if isinstance(na, dict):
            try:
                expr = na["required"]["nodeSelectorTerms"][0][
                    "matchExpressions"][0]
                if expr.get("key") == "kubernetes.io/hostname":
                    out["node_name"] = expr["values"][0]
            except (KeyError, IndexError):
                pass
        return out


def _parse_gi(quantity) -> int:
    """Any k8s quantity → whole Gi ('10Gi'→10, '500Mi'→1, '1Ti'→1024).

    Delegates to serde's general quantity parser; floors at 1Gi since the
    internal fields are whole-Gi sizes."""
    from tpu_on_k8s.utils.serde import _parse_quantity

    if isinstance(quantity, (int, float)):
        return max(1, round(float(quantity) / 2**30))
    return max(1, round(_parse_quantity(str(quantity)) / 2**30))


@dataclass
class PersistentVolume:
    api_version: str = "v1"
    kind: str = "PersistentVolume"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = "Pending"  # Pending | Bound


@dataclass
class PersistentVolumeClaimSpec:
    """Wire hooks emit the conformant core/v1 shape: ``resources.requests.
    storage`` as a quantity and ``accessModes`` (required by real apiserver
    validation — a claim without them is rejected)."""

    volume_name: str = ""
    storage_gi: int = 10
    access_modes: list = field(default_factory=lambda: ["ReadWriteOnce"])

    @staticmethod
    def __wire_out__(d):
        out: dict = {
            "accessModes": d.get("accessModes") or ["ReadWriteOnce"],
            "resources": {"requests": {
                "storage": f"{d.get('storageGi', 10)}Gi"}},
        }
        if d.get("volumeName"):
            out["volumeName"] = d["volumeName"]
        return out

    @staticmethod
    def __wire_in__(d):
        res = d.get("resources")
        if not isinstance(res, dict):
            return d  # internal snake_case form
        out: dict = {"volume_name": d.get("volumeName") or ""}
        if d.get("accessModes"):
            out["access_modes"] = d["accessModes"]
        storage = (res.get("requests") or {}).get("storage")
        if storage is not None:
            out["storage_gi"] = _parse_gi(storage)
        return out


@dataclass
class PersistentVolumeClaim:
    api_version: str = "v1"
    kind: str = "PersistentVolumeClaim"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(default_factory=PersistentVolumeClaimStatus)


class StorageProvider(Protocol):
    """Reference Storage interface (pkg/storage/interface.go:26-35)."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume: ...
    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None: ...
    def get_model_mount_path(self, mv: ModelVersion) -> str: ...


def _mount(spec: PodSpec, volume: Volume, mount_path: str) -> None:
    if not any(v.name == volume.name for v in spec.volumes):
        spec.volumes.append(volume)
    for c in spec.containers:
        if not any(m.name == volume.name for m in c.volume_mounts):
            c.volume_mounts.append(VolumeMount(name=volume.name, mount_path=mount_path))


class LocalStorageProvider:
    """hostPath PV + node-affinity pin (reference local_storage.go:36-106)."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume:
        ls = mv.spec.storage.local_storage
        return PersistentVolume(
            metadata=ObjectMeta(name=pv_name, namespace=""),
            spec=PersistentVolumeSpec(
                host_path=ls.path, node_name=ls.node_name,
                claim_ref=f"{mv.metadata.namespace}/{pv_name}"),
        )

    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None:
        ls = mv.spec.storage.local_storage
        _mount(spec, Volume(name="model-volume", host_path=ls.path),
               self.get_model_mount_path(mv))
        if ls.node_name:
            spec.node_name = ls.node_name

    def get_model_mount_path(self, mv: ModelVersion) -> str:
        return constants.DEFAULT_MODEL_PATH


class NFSProvider:
    """Reference nfs.go:37-90."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume:
        nfs = mv.spec.storage.nfs
        return PersistentVolume(
            metadata=ObjectMeta(name=pv_name, namespace=""),
            spec=PersistentVolumeSpec(
                nfs_server=nfs.server, nfs_path=nfs.path,
                access_modes=["ReadWriteMany"],
                claim_ref=f"{mv.metadata.namespace}/{pv_name}"),
        )

    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None:
        nfs = mv.spec.storage.nfs
        _mount(spec, Volume(name="model-volume", nfs_server=nfs.server, nfs_path=nfs.path),
               self.get_model_mount_path(mv))

    def get_model_mount_path(self, mv: ModelVersion) -> str:
        return mv.spec.storage.nfs.mounted_path or constants.DEFAULT_MODEL_PATH


class GCSProvider:
    """GCS bucket (new): PV modeled as a bucket reference; in-cluster this is a
    GCS FUSE CSI volume."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume:
        gcs = mv.spec.storage.gcs
        return PersistentVolume(
            metadata=ObjectMeta(name=pv_name, namespace=""),
            spec=PersistentVolumeSpec(
                gcs_bucket=gcs.bucket, gcs_prefix=gcs.prefix,
                access_modes=["ReadWriteMany"],
                claim_ref=f"{mv.metadata.namespace}/{pv_name}"),
        )

    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None:
        gcs = mv.spec.storage.gcs
        _mount(spec, Volume(name="model-volume", host_path=f"gcs://{gcs.bucket}/{gcs.prefix}"),
               self.get_model_mount_path(mv))

    def get_model_mount_path(self, mv: ModelVersion) -> str:
        return mv.spec.storage.gcs.mounted_path or constants.DEFAULT_MODEL_PATH


def provider_for_storage(storage: Storage) -> Optional[StorageProvider]:
    """Pick by set field (reference registry.go:36-44)."""
    if storage.local_storage is not None:
        return LocalStorageProvider()
    if storage.nfs is not None:
        return NFSProvider()
    if storage.gcs is not None:
        return GCSProvider()
    return None


def volume_for_storage(storage: Storage) -> Optional[Volume]:
    """The model-output volume injected into training pods
    (reference addModelPathEnv, controllers/common/job.go:557-581)."""
    if storage.local_storage is not None:
        return Volume(name="model-volume", host_path=storage.local_storage.path)
    if storage.nfs is not None:
        return Volume(name="model-volume", nfs_server=storage.nfs.server,
                      nfs_path=storage.nfs.path)
    if storage.gcs is not None:
        return Volume(name="model-volume",
                      host_path=f"gcs://{storage.gcs.bucket}/{storage.gcs.prefix}")
    return None
