"""Storage providers: PV creation + pod volume injection per storage flavor.

Analog of /root/reference/pkg/storage/{interface.go,local_storage.go,nfs.go,
registry/registry.go}: the provider is picked by which field of the tagged
``Storage`` union is set (registry.go:36-44). GCS is new — the idiomatic artifact
store for TPU-on-GKE (mounted via GCS FUSE CSI in a real cluster; modeled as a
volume here).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import ObjectMeta, PodSpec, Volume, VolumeMount
from tpu_on_k8s.api.model_types import ModelVersion, Storage


@dataclass
class PersistentVolumeSpec:
    capacity_gi: int = 10
    access_modes: list = field(default_factory=lambda: ["ReadWriteOnce"])
    host_path: Optional[str] = None
    node_name: Optional[str] = None  # node-affinity pin for local storage
    nfs_server: Optional[str] = None
    nfs_path: Optional[str] = None
    gcs_bucket: Optional[str] = None
    gcs_prefix: Optional[str] = None
    claim_ref: str = ""


@dataclass
class PersistentVolume:
    api_version: str = "v1"
    kind: str = "PersistentVolume"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = "Pending"  # Pending | Bound


@dataclass
class PersistentVolumeClaimSpec:
    volume_name: str = ""
    storage_gi: int = 10


@dataclass
class PersistentVolumeClaim:
    api_version: str = "v1"
    kind: str = "PersistentVolumeClaim"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(default_factory=PersistentVolumeClaimStatus)


class StorageProvider(Protocol):
    """Reference Storage interface (pkg/storage/interface.go:26-35)."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume: ...
    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None: ...
    def get_model_mount_path(self, mv: ModelVersion) -> str: ...


def _mount(spec: PodSpec, volume: Volume, mount_path: str) -> None:
    if not any(v.name == volume.name for v in spec.volumes):
        spec.volumes.append(volume)
    for c in spec.containers:
        if not any(m.name == volume.name for m in c.volume_mounts):
            c.volume_mounts.append(VolumeMount(name=volume.name, mount_path=mount_path))


class LocalStorageProvider:
    """hostPath PV + node-affinity pin (reference local_storage.go:36-106)."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume:
        ls = mv.spec.storage.local_storage
        return PersistentVolume(
            metadata=ObjectMeta(name=pv_name, namespace=""),
            spec=PersistentVolumeSpec(
                host_path=ls.path, node_name=ls.node_name,
                claim_ref=f"{mv.metadata.namespace}/{pv_name}"),
        )

    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None:
        ls = mv.spec.storage.local_storage
        _mount(spec, Volume(name="model-volume", host_path=ls.path),
               self.get_model_mount_path(mv))
        if ls.node_name:
            spec.node_name = ls.node_name

    def get_model_mount_path(self, mv: ModelVersion) -> str:
        return constants.DEFAULT_MODEL_PATH


class NFSProvider:
    """Reference nfs.go:37-90."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume:
        nfs = mv.spec.storage.nfs
        return PersistentVolume(
            metadata=ObjectMeta(name=pv_name, namespace=""),
            spec=PersistentVolumeSpec(
                nfs_server=nfs.server, nfs_path=nfs.path,
                access_modes=["ReadWriteMany"],
                claim_ref=f"{mv.metadata.namespace}/{pv_name}"),
        )

    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None:
        nfs = mv.spec.storage.nfs
        _mount(spec, Volume(name="model-volume", nfs_server=nfs.server, nfs_path=nfs.path),
               self.get_model_mount_path(mv))

    def get_model_mount_path(self, mv: ModelVersion) -> str:
        return mv.spec.storage.nfs.mounted_path or constants.DEFAULT_MODEL_PATH


class GCSProvider:
    """GCS bucket (new): PV modeled as a bucket reference; in-cluster this is a
    GCS FUSE CSI volume."""

    def create_persistent_volume(self, mv: ModelVersion, pv_name: str) -> PersistentVolume:
        gcs = mv.spec.storage.gcs
        return PersistentVolume(
            metadata=ObjectMeta(name=pv_name, namespace=""),
            spec=PersistentVolumeSpec(
                gcs_bucket=gcs.bucket, gcs_prefix=gcs.prefix,
                access_modes=["ReadWriteMany"],
                claim_ref=f"{mv.metadata.namespace}/{pv_name}"),
        )

    def add_model_volume_to_pod_spec(self, mv: ModelVersion, spec: PodSpec) -> None:
        gcs = mv.spec.storage.gcs
        _mount(spec, Volume(name="model-volume", host_path=f"gcs://{gcs.bucket}/{gcs.prefix}"),
               self.get_model_mount_path(mv))

    def get_model_mount_path(self, mv: ModelVersion) -> str:
        return mv.spec.storage.gcs.mounted_path or constants.DEFAULT_MODEL_PATH


def provider_for_storage(storage: Storage) -> Optional[StorageProvider]:
    """Pick by set field (reference registry.go:36-44)."""
    if storage.local_storage is not None:
        return LocalStorageProvider()
    if storage.nfs is not None:
        return NFSProvider()
    if storage.gcs is not None:
        return GCSProvider()
    return None


def volume_for_storage(storage: Storage) -> Optional[Volume]:
    """The model-output volume injected into training pods
    (reference addModelPathEnv, controllers/common/job.go:557-581)."""
    if storage.local_storage is not None:
        return Volume(name="model-volume", host_path=storage.local_storage.path)
    if storage.nfs is not None:
        return Volume(name="model-volume", nfs_server=storage.nfs.server,
                      nfs_path=storage.nfs.path)
    if storage.gcs is not None:
        return Volume(name="model-volume",
                      host_path=f"gcs://{storage.gcs.bucket}/{storage.gcs.prefix}")
    return None
