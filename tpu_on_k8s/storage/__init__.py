"""Model-artifact storage providers (reference /root/reference/pkg/storage/)."""

from tpu_on_k8s.storage.providers import (
    GCSProvider,
    LocalStorageProvider,
    NFSProvider,
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimSpec,
    PersistentVolumeSpec,
    provider_for_storage,
    volume_for_storage,
)
