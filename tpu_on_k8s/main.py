"""Manager entry point: flag surface + controller wiring (reference main.go).

Mirrors the reference operator's process wiring (main.go:50-120): parse
flags and feature gates, construct the cluster client, register the gang
scheduler, wire every controller (TPUJob, elastic, autoscaler, ModelVersion,
InferenceService, the serving fleet autoscaler), start the coordinator loop
and the metrics server, then run the manager.

The cluster backend is pluggable: the in-process `InMemoryCluster` is the
default (tests / local driver — the analog of envtest); a real GKE backend
implements the same create/get/list/update/patch/watch surface against the
API server. Leader election belongs to that backend (a k8s Lease), not to
this wiring.

Run: ``python -m tpu_on_k8s.main --help``.
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import Optional, Tuple

import tpu_on_k8s.api  # noqa: F401  — anchor the api→gang→client import cycle
from tpu_on_k8s.client import InMemoryCluster
from tpu_on_k8s.controller.autoscaler import setup_elastic_autoscaler
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.elastic import ElasticController
from tpu_on_k8s.controller.failover import CRRRestarter, InMemoryRestarter
from tpu_on_k8s.controller.fleetautoscaler import setup_fleet_autoscaler
from tpu_on_k8s.controller.inferenceservice import (
    setup_inferenceservice_controller,
)
from tpu_on_k8s.controller.modelversion import setup_modelversion_controller
from tpu_on_k8s.controller.runtime import Manager
from tpu_on_k8s.controller.tpujob import setup_tpujob_controller
from tpu_on_k8s.coordinator.broker import CapacityBroker
from tpu_on_k8s.coordinator.core import Coordinator
from tpu_on_k8s.features import features
from tpu_on_k8s.gang.scheduler import GANG_SCHEDULER_NAME, default_registry
from tpu_on_k8s.metrics.metrics import (
    AutoscaleMetrics,
    BrokerMetrics,
    JobMetrics,
    LedgerMetrics,
    SLOMetrics,
    serve,
)
from tpu_on_k8s.obs.ledger import DecisionLedger


def parse_port_range(spec: str) -> Tuple[int, int]:
    lo, _, hi = spec.partition("-")
    return int(lo), int(hi)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-on-k8s-manager",
        description="TPU-native distributed training operator")
    # the reference's pflag surface (main.go:58-66)
    p.add_argument("--metrics-port", type=int, default=8443)
    p.add_argument("--enable-gang-scheduling", default=True,
                   action=argparse.BooleanOptionalAction)
    p.add_argument("--max-concurrent-reconciles", type=int, default=1)
    p.add_argument("--hostnetwork-port-range", default="20000-30000")
    p.add_argument("--model-image-builder",
                   default="gcr.io/kaniko-project/executor:latest")
    p.add_argument("--feature-gates", default="",
                   help="Comma-separated Name=bool overrides, e.g. "
                        "GangScheduling=true,JobCoordinator=false")
    # tunables the reference hard-coded (SURVEY §5.6)
    p.add_argument("--coordinator-period-seconds", type=float, default=0.1)
    p.add_argument("--elastic-loop-period-seconds", type=float, default=30.0)
    p.add_argument("--profile-dir", default="",
                   help="inject TPU_ON_K8S_PROFILE_DIR into slice pods: "
                        "train loops capture an XLA trace there "
                        "(utils/profiling.py; empty = off)")
    p.add_argument("--profiler-port", type=int, default=0,
                   help="inject TPU_ON_K8S_PROFILER_PORT into slice pods: "
                        "train loops serve the live JAX profiler on it "
                        "(0 = off)")
    p.add_argument("--serving-autoscale-period-seconds", type=float,
                   default=15.0,
                   help="Tick period of the serving SLO autoscaler "
                        "(InferenceServices with spec.autoscale set)")
    p.add_argument("--broker-capacity-chips", type=int, default=0,
                   help="Total chip capacity of the capacity broker's "
                        "slice market (coordinator/broker.py): serving "
                        "fleets, elastic training, and the batch lane "
                        "bid for one shared pool, with degrade-before-"
                        "take pressure valves and graceful preemption "
                        "(0 = no broker, market-free operation)")
    p.add_argument("--broker-period-seconds", type=float, default=10.0,
                   help="Tick period of the capacity broker's market "
                        "clearing loop")
    p.add_argument("--once", action="store_true",
                   help="Pump controllers to quiescence and exit (smoke mode)")
    p.add_argument("--leader-elect", default=False,
                   action=argparse.BooleanOptionalAction,
                   help="Run controllers only while holding the election "
                        "lease (reference main.go:77-83)")
    p.add_argument("--leader-identity", default="",
                   help="Election identity (default: hostname-pid)")
    p.add_argument("--cluster-backend", default="auto",
                   choices=["auto", "memory", "rest"],
                   help="auto: REST when a kubeconfig/in-cluster config "
                        "resolves, else in-memory (tests/smoke)")
    p.add_argument("--api-server", default="",
                   help="API server URL for the REST backend (overrides "
                        "kubeconfig resolution)")
    # the slice gang-admission actor (our Volcano-role deliverable)
    p.add_argument("--enable-slice-scheduler", default=False,
                   action=argparse.BooleanOptionalAction,
                   help="Run the TPU slice gang-admission loop in-process "
                        "with the manager (single-binary deployments)")
    p.add_argument("--scheduler-only", action="store_true",
                   help="Run ONLY the slice gang-admission loop (the "
                        "dedicated scheduler Deployment, config/scheduler/)")
    p.add_argument("--node-pools", default="",
                   help="Comma-separated finite slice inventory: "
                        "name=accelerator:topology:num_slices[:cpu=C][:mem=M]")
    p.add_argument("--node-pools-file", default="",
                   help="YAML list of node pools (the mounted ConfigMap form)")
    p.add_argument("--scheduler-period-seconds", type=float, default=0.1)
    # in-place restart executor (the OpenKruise CRR protocol)
    p.add_argument("--restart-executor", default="auto",
                   choices=["auto", "crr", "memory"],
                   help="In-place restart executor: crr posts "
                        "ContainerRecreateRequests for the node agent to "
                        "honor (any real cluster); memory forges pod status "
                        "in-process (in-memory backend ONLY); auto picks by "
                        "backend")
    p.add_argument("--crr-wait-seconds", type=float, default=60.0,
                   help="Deadline (measured from the CRR's creation, across "
                        "reconcile passes — never an in-pass wait) for a "
                        "node agent to complete a CRR before the operator "
                        "falls back to recreate; covers a real CRI "
                        "stop+kubelet-recreate cycle")
    # the node-agent actor (our OpenKruise-daemon-role deliverable)
    p.add_argument("--node-agent-only", action="store_true",
                   help="Run ONLY the CRR node agent (the DaemonSet role, "
                        "config/nodeagent/)")
    p.add_argument("--node-name", default="",
                   help="Node this agent serves (downward-API injected in "
                        "the DaemonSet); empty serves every node")
    p.add_argument("--node-agent-resync-seconds", type=float, default=300.0,
                   help="Slow-resync period of the node agent's CRR "
                        "informer (the agent is watch-driven; this is the "
                        "belt-and-braces re-list, not a poll)")
    p.add_argument("--runtime", default="auto", choices=["auto", "cri", "sim"],
                   help="Container runtime behind the node agent: cri stops "
                        "containers through the node's CRI socket and lets "
                        "the kubelet recreate them (real nodes; pod status "
                        "never written); sim writes pod status through the "
                        "API server (tests/simulated clusters ONLY); auto "
                        "picks cri when the CRI socket exists")
    p.add_argument("--cri-endpoint",
                   default="unix:///run/containerd/containerd.sock",
                   help="CRI runtime socket (the DaemonSet hostPath-mounts "
                        "it)")
    p.add_argument("--crictl-path", default="crictl",
                   help="crictl binary the CRI runtime shells out to")
    p.add_argument("--cri-wait-seconds", type=float, default=60.0,
                   help="How long the node agent waits for the kubelet to "
                        "recreate stopped containers before failing the CRR")
    return p


def build_node_pools(args: argparse.Namespace):
    from tpu_on_k8s.gang.scheduler import load_node_pools_file, parse_node_pools

    pools = []
    if getattr(args, "node_pools", ""):
        pools.extend(parse_node_pools(args.node_pools))
    if getattr(args, "node_pools_file", ""):
        pools.extend(load_node_pools_file(args.node_pools_file))
    return pools


def build_restarter(args: argparse.Namespace, cluster):
    """Select the in-place restart executor by backend (VERDICT r3 #1): the
    operator may forge pod status ONLY against the in-memory cluster, where
    no kubelet owns that state. Any real (REST) API server gets the CRR
    protocol — post a ContainerRecreateRequest, let the node agent restart
    the containers (reference failover.go:210-307)."""
    mode = getattr(args, "restart_executor", "auto")
    if mode == "auto":
        from tpu_on_k8s.client.rest import RestCluster

        mode = "crr" if isinstance(cluster, RestCluster) else "memory"
    if mode == "crr":
        return CRRRestarter(
            cluster, wait_seconds=getattr(args, "crr_wait_seconds", 5.0))
    if isinstance(cluster, InMemoryCluster):
        return InMemoryRestarter()
    raise SystemExit(
        "--restart-executor memory forges kubelet-owned pod status and is "
        "only legal against --cluster-backend memory; use crr")


def build_runtime(args: argparse.Namespace, cluster):
    """Select the node agent's container runtime (VERDICT r4 #3): a real
    node gets the CRI shim — stop containers through the runtime socket and
    let the kubelet recreate them, pod status never written. ``sim`` (the
    KubeletSim status-write surface) is only legal where no kubelet owns pod
    status: tests, local drivers, simulated clusters."""
    import os

    from tpu_on_k8s.client.cri import CriRuntime

    mode = getattr(args, "runtime", "auto")
    endpoint = getattr(args, "cri_endpoint",
                       "unix:///run/containerd/containerd.sock")
    if mode == "auto":
        socket_path = endpoint[len("unix://"):] if endpoint.startswith(
            "unix://") else endpoint
        mode = "cri" if os.path.exists(socket_path) else "sim"
    if mode == "cri":
        return CriRuntime(
            crictl=getattr(args, "crictl_path", "crictl"), endpoint=endpoint,
            wait_seconds=getattr(args, "cri_wait_seconds", 60.0))
    from tpu_on_k8s.client.testing import KubeletSim

    return KubeletSim(cluster)


def build_cluster(args: argparse.Namespace):
    """Select the cluster backend (reference main.go:77-83 — the manager
    always dials a real API server; here `memory` keeps the envtest-style
    in-process mode as an explicit choice)."""
    backend = getattr(args, "cluster_backend", "auto")
    url = getattr(args, "api_server", "")
    creds = None
    if backend in ("auto", "rest") and not url:
        from tpu_on_k8s.client import kubeconfig

        cfg = kubeconfig.resolve()
        url = kubeconfig.server_url(cfg) or ""
        # inline kubeconfig credentials materialize into a private tempdir
        # that credentials() creates lazily and removes at exit
        creds = kubeconfig.credentials(cfg)
    if backend == "rest" or (backend == "auto" and url):
        if not url:
            raise SystemExit(
                "--cluster-backend rest requires --api-server or a "
                "resolvable kubeconfig/in-cluster config")
        from tpu_on_k8s.client.rest import RestCluster

        if creds is None:
            return RestCluster(url)
        return RestCluster(url, token_path=creds.token_path,
                           ca_path=creds.ca_path, token=creds.token,
                           client_cert_path=creds.client_cert_path,
                           client_key_path=creds.client_key_path)
    return InMemoryCluster()


class Operator:
    """All wired components; ``start``/``stop`` or one-shot ``run_once``."""

    def __init__(self, args: argparse.Namespace,
                 cluster: Optional[InMemoryCluster] = None):
        self.cluster = cluster if cluster is not None else build_cluster(args)
        self.manager = Manager()
        self.metrics = JobMetrics()
        # both backends count conflict retries against the operator's own
        # metrics (client/rest.py + client/cluster.py update_with_retry)
        self.cluster.metrics = self.metrics
        self.gates = (features.FeatureGates.parse(args.feature_gates)
                      if args.feature_gates else features.FeatureGates())
        self.config = JobControllerConfig(
            enable_gang_scheduling=args.enable_gang_scheduling,
            max_concurrent_reconciles=args.max_concurrent_reconciles,
            hostnetwork_port_range=parse_port_range(args.hostnetwork_port_range),
            model_image_builder=args.model_image_builder,
            coordinator_period_seconds=args.coordinator_period_seconds,
            elastic_loop_period_seconds=args.elastic_loop_period_seconds,
            serving_autoscale_period_seconds=getattr(
                args, "serving_autoscale_period_seconds", 15.0),
            profile_dir=getattr(args, "profile_dir", ""),
            profiler_port=getattr(args, "profiler_port", 0),
        )

        gang = None
        if (self.config.enable_gang_scheduling
                and self.gates.enabled(features.GANG_SCHEDULING)):
            registry = default_registry(self.cluster)
            gang = registry.get(GANG_SCHEDULER_NAME)
        self.coordinator = None
        if self.gates.enabled(features.JOB_COORDINATOR):
            self.coordinator = Coordinator(
                self.cluster, metrics=self.metrics,
                period_seconds=self.config.coordinator_period_seconds)
        restarter = build_restarter(args, self.cluster)
        self.elastic = ElasticController(self.cluster, restarter=restarter)
        self.engine = setup_tpujob_controller(
            self.cluster, self.manager, config=self.config, gates=self.gates,
            gang_scheduler=gang, restarter=restarter, metrics=self.metrics,
            coordinator=self.coordinator, elastic_controller=self.elastic)
        # decision provenance (obs/ledger.py): ONE ledger shared by the
        # elastic and fleet autoscalers, so the operator's control-plane
        # decisions form one causal record stream; its telemetry rides
        # the operator registry (decisions{loop|outcome}, commit
        # failures, the open-effect-horizons gauge)
        self.ledger_metrics = LedgerMetrics(registry=self.metrics.registry)
        self.ledger = DecisionLedger(metrics=self.ledger_metrics)
        # the capacity broker (coordinator/broker.py): one slice market
        # both autoscalers bid on — scale-ups ask it for chips before
        # they patch, and its escalation ladder (degrade → harvest →
        # preempt → typed refusal) lands every transition on the same
        # ledger. Opt-in by capacity: 0 chips = no broker, and both
        # autoscalers run market-free, byte-identical to before.
        self.broker = None
        self.broker_metrics = None
        capacity = getattr(args, "broker_capacity_chips", 0)
        if capacity > 0:
            self.broker_metrics = BrokerMetrics(
                registry=self.metrics.registry)
            self.broker = CapacityBroker(
                capacity, ledger=self.ledger,
                metrics=self.broker_metrics,
                period_s=getattr(args, "broker_period_seconds", 10.0))
        self.autoscaler = setup_elastic_autoscaler(
            self.cluster, config=self.config, metrics=self.metrics,
            ledger=self.ledger, broker=self.broker)
        self.modelversion = setup_modelversion_controller(
            self.cluster, self.manager, config=self.config)
        self.inferenceservice = setup_inferenceservice_controller(
            self.cluster, self.manager, config=self.config)
        # the serving twin of the elastic autoscaler: fleet load →
        # InferenceService.spec.replicas (controller/fleetautoscaler.py).
        # Shares the operator's registry so --metrics-port scrapes the
        # autoscale series alongside the job series.
        self.autoscale_metrics = AutoscaleMetrics(
            registry=self.metrics.registry)
        # SLO telemetry plane (obs/slo.py, spec.slo services): burn-rate
        # / error-budget gauges + per-tenant accounting counters on the
        # same scrape endpoint
        self.slo_metrics = SLOMetrics(registry=self.metrics.registry)
        self.fleetautoscaler = setup_fleet_autoscaler(
            self.cluster, config=self.config,
            metrics=self.autoscale_metrics,
            slo_metrics=self.slo_metrics,
            ledger=self.ledger, broker=self.broker)
        self.scheduler_loop = None
        if getattr(args, "enable_slice_scheduler", False):
            from tpu_on_k8s.gang.scheduler import (
                SliceGangAdmission,
                SliceSchedulerLoop,
            )
            self.scheduler_loop = SliceSchedulerLoop(
                SliceGangAdmission(self.cluster, pools=build_node_pools(args)),
                period_seconds=getattr(args, "scheduler_period_seconds", 0.1))
        self.elector = None
        if getattr(args, "leader_elect", False):
            import os
            import socket

            from tpu_on_k8s.controller.leaderelection import LeaderElector
            identity = (getattr(args, "leader_identity", "")
                        or f"{socket.gethostname()}-{os.getpid()}")
            self.elector = LeaderElector(self.cluster, identity)
        self._metrics_server = None
        self._workers_lock = threading.Lock()
        self._workers_running = False

    def run_once(self) -> int:
        """Single quiescence pump (smoke/test mode)."""
        if self.coordinator is not None:
            self.coordinator.schedule_once()
        return self.manager.run_until_idle()

    def _start_workers(self) -> None:
        # re-acquiring leadership must not stack a second set of threads on
        # top of a still-running first set (double-reconcile in-process);
        # coordinator.run()/autoscaler.run() manage their own threads
        with self._workers_lock:
            if self._workers_running:
                return
            self._workers_running = True
            self.manager.start(
                workers_per_controller=self.config.max_concurrent_reconciles)
            if self.coordinator is not None:
                self.coordinator.run()
            self.autoscaler.run()
            self.fleetautoscaler.run()
            if self.broker is not None:
                self.broker.run()
            if self.scheduler_loop is not None:
                self.scheduler_loop.run()

    def _stop_workers(self) -> None:
        """Mirror of _start_workers: losing the lease must stop *every*
        reconciling thread, not just the manager — a coordinator or
        autoscaler that keeps running on a non-leader is a split brain."""
        with self._workers_lock:
            if not self._workers_running:
                return
            self._workers_running = False
            if self.coordinator is not None:
                self.coordinator.stop()
            self.autoscaler.stop()
            self.fleetautoscaler.stop()
            if self.broker is not None:
                self.broker.stop()
            if self.scheduler_loop is not None:
                self.scheduler_loop.stop()
            self.manager.stop()

    def start(self, metrics_port: int = 0) -> None:
        if self.elector is not None:
            # controllers run only while we hold the lease; losing it stops
            # them so a split brain cannot double-reconcile
            self.elector.on_started_leading = self._start_workers
            self.elector.on_stopped_leading = self._stop_workers
            self.elector.start()
        else:
            self._start_workers()
        if metrics_port:
            self._metrics_server = serve(self.metrics, metrics_port)

    def stop(self) -> None:
        if self.elector is not None:
            self.elector.stop()
        self._stop_workers()
        # REST backends run informer threads; stop their reconnect loops so a
        # stopped manager doesn't keep dialing the apiserver.
        close = getattr(self.cluster, "close", None)
        if callable(close):
            close()


def _run_forever(loop, cluster) -> int:
    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    loop.stop()
    close = getattr(cluster, "close", None)
    if callable(close):
        close()
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.node_agent_only:
        # Dedicated node actor (its own DaemonSet): no controllers, just the
        # CRR executor against the cluster backend.
        from tpu_on_k8s.client.nodeagent import NodeAgentLoop

        cluster = build_cluster(args)
        agent = NodeAgentLoop(
            cluster, node_name=args.node_name or None,
            resync_seconds=args.node_agent_resync_seconds,
            runtime=build_runtime(args, cluster))
        agent.start()
        return _run_forever(agent, cluster)
    if args.scheduler_only:
        # Dedicated admission actor (its own Deployment): no controllers,
        # just the slice scheduler loop against the cluster backend.
        from tpu_on_k8s.gang.scheduler import (
            SliceGangAdmission,
            SliceSchedulerLoop,
        )
        pools = build_node_pools(args)
        if not pools:
            # The dedicated admission actor without inventory would fall into
            # the unconstrained test-only path and stamp fabricated node
            # names onto real pods — refuse loudly instead.
            raise SystemExit(
                "--scheduler-only requires a non-empty slice inventory "
                "(--node-pools or --node-pools-file)")
        cluster = build_cluster(args)
        admission = SliceGangAdmission(cluster, pools=pools)
        loop = SliceSchedulerLoop(
            admission, period_seconds=args.scheduler_period_seconds)
        if args.leader_elect:
            # HA admission (VERDICT r3 missing #3): replicas contend for the
            # scheduler's OWN lease; only the holder syncs, and a takeover
            # rebuilds the slice inventory from cluster state first — two
            # actors admitting from independent inventories is the
            # double-booking hazard.
            import os
            import socket

            from tpu_on_k8s.controller.leaderelection import LeaderElector

            def lead():
                admission.resync()
                loop.run()

            elector = LeaderElector(
                cluster,
                (args.leader_identity or f"{socket.gethostname()}-{os.getpid()}"),
                lease_name="tpu-on-k8s-scheduler-election",
                on_started_leading=lead, on_stopped_leading=loop.stop)
            elector.start()

            class _Both:
                def stop(self):
                    elector.stop()
                    loop.stop()
            return _run_forever(_Both(), cluster)
        loop.run()
        return _run_forever(loop, cluster)
    operator = Operator(args)
    if args.once:
        processed = operator.run_once()
        print(f"quiescent after {processed} reconciles")
        return 0
    operator.start(metrics_port=args.metrics_port)
    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    operator.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
