"""REST resource registry: kind ↔ (group, version, plural) ↔ Python class.

The reference gets this mapping from apimachinery scheme registration
(/root/reference/apis/add_types.go:25-37) plus the generated clientset's
per-resource REST paths (client/clientset/versioned/typed/train/v1alpha1/
torchjob.go). Here one explicit table serves both the API server's router
and the typed REST client.

Scoping matches real Kubernetes: PersistentVolume and PriorityClass are
cluster-scoped (no ``namespaces/{ns}`` path segment); everything else is
namespaced.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import (
    ConfigMap,
    Event,
    Pod,
    PriorityClass,
    ResourceQuota,
    Service,
)
from tpu_on_k8s.api.crr import ContainerRecreateRequest
from tpu_on_k8s.api.inference_types import InferenceService
from tpu_on_k8s.api.model_types import Model, ModelVersion
from tpu_on_k8s.api.types import TPUJob


@dataclass(frozen=True)
class ResourceType:
    kind: str
    cls: type
    group: str          # "" = core ("/api/v1")
    version: str
    plural: str
    namespaced: bool = True

    @property
    def prefix(self) -> str:
        if not self.group:
            return f"/api/{self.version}"
        return f"/apis/{self.group}/{self.version}"

    def collection_path(self, namespace: str) -> str:
        if not self.namespaced:
            return f"{self.prefix}/{self.plural}"
        return f"{self.prefix}/namespaces/{namespace}/{self.plural}"

    def item_path(self, namespace: str, name: str) -> str:
        return f"{self.collection_path(namespace)}/{name}"

    def all_namespaces_path(self) -> str:
        return f"{self.prefix}/{self.plural}"


def _build() -> Tuple[Dict[str, ResourceType], Dict[Tuple[str, str], ResourceType]]:
    # Imported lazily where needed to respect the api→gang→client cycle
    # anchored in main.py; these two live outside tpu_on_k8s.api.
    from tpu_on_k8s.controller.leaderelection import Lease
    from tpu_on_k8s.gang.scheduler import PodGroup
    from tpu_on_k8s.storage.providers import (
        PersistentVolume,
        PersistentVolumeClaim,
    )

    tpu_group = constants.API_GROUP
    tpu_ver = constants.API_VERSION
    rows = [
        ResourceType("Pod", Pod, "", "v1", "pods"),
        ResourceType("Service", Service, "", "v1", "services"),
        ResourceType("ConfigMap", ConfigMap, "", "v1", "configmaps"),
        ResourceType("ResourceQuota", ResourceQuota, "", "v1", "resourcequotas"),
        ResourceType("Event", Event, "", "v1", "events"),
        ResourceType("PersistentVolume", PersistentVolume, "", "v1",
                     "persistentvolumes", namespaced=False),
        ResourceType("PersistentVolumeClaim", PersistentVolumeClaim, "", "v1",
                     "persistentvolumeclaims"),
        ResourceType("PriorityClass", PriorityClass, "scheduling.k8s.io", "v1",
                     "priorityclasses", namespaced=False),
        ResourceType("Lease", Lease, "coordination.k8s.io", "v1", "leases"),
        ResourceType("PodGroup", PodGroup, "scheduling.distributed.tpu.io",
                     "v1beta1", "podgroups"),
        ResourceType("ContainerRecreateRequest", ContainerRecreateRequest,
                     "apps.distributed.tpu.io", "v1alpha1",
                     "containerrecreaterequests"),
        ResourceType(constants.KIND_TPUJOB, TPUJob, tpu_group, tpu_ver,
                     "tpujobs"),
        ResourceType(constants.KIND_MODEL, Model, tpu_group, tpu_ver, "models"),
        ResourceType(constants.KIND_MODELVERSION, ModelVersion, tpu_group,
                     tpu_ver, "modelversions"),
        ResourceType(constants.KIND_INFERENCESERVICE, InferenceService,
                     tpu_group, tpu_ver, "inferenceservices"),
    ]
    return ({r.kind: r for r in rows},
            {(r.group, r.plural): r for r in rows})


_BY_KIND: Optional[Dict[str, ResourceType]] = None
_BY_ROUTE: Optional[Dict[Tuple[str, str], ResourceType]] = None


def _ensure() -> None:
    global _BY_KIND, _BY_ROUTE
    if _BY_KIND is None:
        _BY_KIND, _BY_ROUTE = _build()


def by_kind(kind: str) -> ResourceType:
    _ensure()
    rt = _BY_KIND.get(kind)
    if rt is None:
        raise KeyError(f"unregistered kind {kind!r}")
    return rt


def by_class(cls: type) -> ResourceType:
    kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
    return by_kind(kind)


def by_route(group: str, plural: str) -> Optional[ResourceType]:
    _ensure()
    return _BY_ROUTE.get((group, plural))


def all_types() -> list:
    _ensure()
    return list(_BY_KIND.values())
