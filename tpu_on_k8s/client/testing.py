"""Kubelet simulation for tests and local runs.

The reference's intended envtest strategy runs a real API server but no kubelet,
so controllers are driven by manipulating pod status (SURVEY §4). ``KubeletSim``
packages those manipulations: admit pods to nodes, run/succeed/fail containers
with exit codes, simulate preemption/eviction. It is also the injectable
container runtime behind the deployable CRR node agent
(``tpu_on_k8s.client.nodeagent.NodeAgentLoop``) — the restart surface a real
CRI shim would implement.
"""
from __future__ import annotations

from typing import Optional

from tpu_on_k8s.api.core import (
    Condition,
    ContainerStateTerminated,
    ContainerStatus,
    Pod,
    PodPhase,
    utcnow,
)
from tpu_on_k8s.client.cluster import InMemoryCluster, NotFoundError


def append_pod_log(cluster, namespace: str, name: str, line: str) -> None:
    """Kubelet-side log injection — the TEST SEAM for pod logs.

    A real training container's stdout reaches ``pods/{name}/log`` via the
    kubelet, not via any client verb, so the REST client deliberately has no
    log-append method (``POST .../pods/{name}/log`` is not a Kubernetes verb;
    see the divergence table in `tpu_on_k8s/client/apiserver.py`). Tests and
    the kubelet sim inject log lines here: directly into the in-memory store,
    or over the test apiserver's private log endpoint for REST backends.
    """
    if hasattr(cluster, "append_pod_log"):       # InMemoryCluster store
        cluster.append_pod_log(namespace, name, line)
        return
    from urllib.parse import quote
    cluster._request(                            # test-only seam into ApiServer
        "POST", f"/api/v1/namespaces/{namespace}/pods/{quote(name)}/log",
        {"line": line})


class KubeletSim:
    def __init__(self, cluster: InMemoryCluster) -> None:
        self.cluster = cluster
        self._ip = 0

    def _set(self, namespace: str, name: str, mutate) -> Pod:
        return self.cluster.update_with_retry(Pod, namespace, name, mutate, subresource="status")

    def run_pod(self, namespace: str, name: str, node: str = "node-0") -> Pod:
        """Pending → Running + Ready, with IP and node assigned."""
        self._ip += 1
        ip = f"10.0.0.{self._ip}"

        def mutate(pod: Pod) -> None:
            pod.status.phase = PodPhase.RUNNING
            pod.status.pod_ip = ip
            pod.status.host_ip = ip
            pod.status.start_time = pod.status.start_time or utcnow()
            pod.status.conditions = [Condition(type="Ready", status="True", last_transition_time=utcnow())]
            pod.status.container_statuses = [
                ContainerStatus(name=c.name, ready=True) for c in pod.spec.containers
            ]
            if not pod.spec.node_name:
                pod.spec.node_name = node

        pod = self.cluster.get(Pod, namespace, name)
        if not pod.spec.node_name:
            # node assignment is a spec write; status subresource won't persist it
            self.cluster.update_with_retry(
                Pod, namespace, name, lambda p: setattr(p.spec, "node_name", node))
        return self._set(namespace, name, mutate)

    def run_all(self, namespace: str, label_selector=None, node: str = "node-0") -> int:
        n = 0
        for pod in self.cluster.list(Pod, namespace, label_selector):
            if pod.status.phase == PodPhase.PENDING and pod.metadata.deletion_timestamp is None:
                self.run_pod(namespace, pod.metadata.name, node=f"{node[:5]}-{n}")
                n += 1
        return n

    def terminate_pod(self, namespace: str, name: str, exit_code: int,
                      reason: str = "", phase: Optional[str] = None) -> Pod:
        """Terminate the main container with an exit code; phase derives from the
        code unless forced."""
        if phase is None:
            phase = PodPhase.SUCCEEDED if exit_code == 0 else PodPhase.FAILED

        def mutate(pod: Pod) -> None:
            pod.status.phase = phase
            pod.status.reason = reason
            pod.status.conditions = [Condition(type="Ready", status="False", last_transition_time=utcnow())]
            pod.status.container_statuses = [
                ContainerStatus(
                    name=c.name,
                    ready=False,
                    terminated=ContainerStateTerminated(exit_code=exit_code, reason=reason),
                )
                for c in pod.spec.containers
            ]

        return self._set(namespace, name, mutate)

    def succeed_pod(self, namespace: str, name: str) -> Pod:
        return self.terminate_pod(namespace, name, 0)

    def fail_pod(self, namespace: str, name: str, exit_code: int = 1, reason: str = "Error") -> Pod:
        return self.terminate_pod(namespace, name, exit_code, reason=reason)

    def log_line(self, namespace: str, name: str, line: str) -> None:
        """Emit a line into the pod's log stream (training stdout analog)."""
        append_pod_log(self.cluster, namespace, name, line)

    def evict_pod(self, namespace: str, name: str) -> Pod:
        """Node-pressure eviction (retryable failure class, failover.go:106-113)."""
        return self.terminate_pod(namespace, name, 137, reason="Evicted", phase=PodPhase.FAILED)

    def recreate_containers(self, namespace: str, name: str,
                            containers: Optional[list] = None,
                            expect_uid: Optional[str] = None) -> Pod:
        """What a CRI container restart looks like from the API server: the
        named containers (all, if empty) come back ready with restart_count
        bumped, and the pod returns to Running.

        ``expect_uid`` pins the pod incarnation: the check runs INSIDE the
        retried mutate (under the update's resourceVersion precondition), so
        a pod recreated under the same name between the caller's read and
        this write can never be forged to Running — it raises NotFound, the
        same outcome as the pod vanishing."""
        wanted = set(containers or [])

        def mutate(pod: Pod) -> None:
            if expect_uid is not None and pod.metadata.uid != expect_uid:
                raise NotFoundError(
                    f"pod {namespace}/{name} incarnation changed "
                    f"(uid {pod.metadata.uid} != {expect_uid})")
            pod.status.phase = PodPhase.RUNNING
            pod.status.reason = ""
            pod.status.conditions = [Condition(
                type="Ready", status="True", last_transition_time=utcnow())]
            if not pod.status.container_statuses:
                pod.status.container_statuses = [
                    ContainerStatus(name=c.name) for c in pod.spec.containers]
            for cs in pod.status.container_statuses:
                if wanted and cs.name not in wanted:
                    continue
                cs.ready = True
                cs.restart_count += 1
                cs.terminated = None

        return self._set(namespace, name, mutate)


class KubeletLoop:
    """Background kubelet: polls for Pending pods and runs them, keyed on pod
    uid so a recreated pod (same name, new uid) runs again — real kubelets key
    on uid the same way. ``scheduled_only=True`` models a kubelet that only
    runs pods a scheduler has bound to a node (the gang-admission tests);
    ``auto_succeed=True`` completes pods as soon as they run (build-pod /
    batch-job sims). Works against any cluster backend (in-memory or REST).
    """

    def __init__(self, cluster, *, scheduled_only: bool = False,
                 auto_succeed: bool = False, poll_seconds: float = 0.02):
        import threading

        self.sim = KubeletSim(cluster)
        self.cluster = cluster
        self.scheduled_only = scheduled_only
        self.auto_succeed = auto_succeed
        self.poll_seconds = poll_seconds
        self._stop = threading.Event()
        self._thread: Optional[object] = None

    def start(self) -> "KubeletLoop":
        import threading

        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="kubelet-loop")
        self._thread.start()
        return self

    def _loop(self) -> None:
        ran = set()
        while not self._stop.is_set():
            for p in self.cluster.list(Pod):
                if p.metadata.deletion_timestamp is not None:
                    continue
                key = (p.metadata.name, p.metadata.uid)
                if (key not in ran and p.status.phase == PodPhase.PENDING
                        and (p.spec.node_name or not self.scheduled_only)):
                    try:
                        self.sim.run_pod(p.metadata.namespace,
                                        p.metadata.name,
                                        node=p.spec.node_name or "node-0")
                        ran.add(key)
                    # analyze: allow[silent-loss] test-harness kubelet racing reconciler deletes; next poll settles
                    except Exception:  # noqa: BLE001 — races with reconciles
                        pass
                elif (self.auto_succeed
                      and p.status.phase == PodPhase.RUNNING):
                    try:
                        self.sim.succeed_pod(p.metadata.namespace,
                                             p.metadata.name)
                    # analyze: allow[silent-loss] same reconciler race on the auto-succeed edge; next poll settles
                    except Exception:  # noqa: BLE001
                        pass
            self._stop.wait(self.poll_seconds)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
