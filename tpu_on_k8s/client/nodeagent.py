"""The CRR node agent — the OpenKruise-daemon role as a deployable actor.

``NodeAgentLoop`` watches ``ContainerRecreateRequest`` objects over its own
cluster connection and executes them against the node's container runtime —
which, from the API server's point of view, is the pod-status surface the
kubelet owns. With it running, the operator's ``CRRRestarter``
(`tpu_on_k8s/controller/failover.py`) never forges pod status; that
separation is what the reference buys by delegating in-place restarts to
kruise's node daemon (controllers/common/failover.go:210-307).

Deployed per node by ``config/nodeagent/daemonset.yaml`` (entrypoint:
``python -m tpu_on_k8s.main --node-agent-only --runtime cri``) under its own
ServiceAccount. On the deployed ``--runtime cri`` path the agent NEVER
writes pod status — it stops containers through the node's CRI socket
(`tpu_on_k8s/client/cri.py`) and the kubelet owns the status surface, so
the node-agent RBAC grants no ``pods/status`` verbs at all. The runtime is
an injectable seam: ``KubeletSim`` (``--runtime sim``) is the status-write
surface for tests / local drivers / simulated clusters where no kubelet
owns pod status — it needs ``pods/status`` re-granted and is never legal on
a real node.
"""
from __future__ import annotations

import threading
from typing import Optional

from tpu_on_k8s.api import crr as crr_api
from tpu_on_k8s.api.core import Pod, utcnow
from tpu_on_k8s.api.crr import ContainerRecreateRequest
from tpu_on_k8s.client.cluster import ConflictError, NotFoundError
from tpu_on_k8s.client.cri import CriError
from tpu_on_k8s.client.testing import KubeletSim


class NodeAgentLoop:
    """Honors ``ContainerRecreateRequest`` objects (the kruise-daemon side
    of reference failover.go:210-307):

    * a Pending CRR whose pod exists (and, for a node-scoped agent, is bound
      to this node) transitions ``Recreating`` → container restart →
      ``Succeeded`` + completion_time;
    * a CRR naming a missing pod — or one whose pod uid no longer matches
      the CRR's pod-uid label — is marked ``Failed`` (the operator falls
      back to delete+recreate on seeing it); the uid is ALSO re-verified
      inside the restart write itself, so a pod replaced mid-flight can
      never be forged to Running;
    * finished CRRs the operator never collected are reaped after
      ``ttl_seconds_after_finished`` (kruise's TTL reaper).

    ``node_name=None`` serves every node — one agent standing in for the
    whole DaemonSet, which is what single-process tests and the local
    driver run.

    EVENT-DRIVEN, not a poll loop: ``start()`` subscribes a watch on the
    CRR kind only (one informer stream per node, not one per resource
    type) and a worker drains a deduplicating key queue. The steady state
    issues NO full-collection LISTs — the round-4 agent LISTed every CRR
    in the cluster every 100 ms from every node, the exact hot loop
    informers exist to kill. A slow resync (``resync_seconds``, default
    5 min) is the belt-and-braces pass for a missed event; TTL reaping of
    finished CRRs is scheduled per object at its expiry instead of being
    rediscovered by polling.
    """

    WATCH_KINDS = frozenset({"ContainerRecreateRequest"})

    def __init__(self, cluster, *, node_name: Optional[str] = None,
                 poll_seconds: float = 0.02, runtime=None,
                 resync_seconds: float = 300.0):
        del poll_seconds  # legacy poll-loop cadence; kept for call compat
        self.cluster = cluster
        self.runtime = runtime if runtime is not None else KubeletSim(cluster)
        self.node_name = node_name
        self.resync_seconds = resync_seconds
        self.executed = 0  # restarts this agent performed (observability)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cond = threading.Condition()
        self._queue: set = set()          # pending (namespace, name) keys
        self._timers: list = []           # TTL-reap timers (cancelled on stop)

    def start(self) -> "NodeAgentLoop":
        self.cluster.watch(self._on_event, kinds=self.WATCH_KINDS)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-agent")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
            # snapshot under the condition: the agent thread rebuilds
            # this list in _schedule_reap — cancelling a concurrent
            # rebuild's OLD list would let a fresh TTL timer escape and
            # fire into a torn-down cluster
            pending, self._timers = self._timers, []
        for t in pending:
            t.cancel()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # ---------------------------------------------------------------- wiring
    def _on_event(self, event) -> None:
        if event.kind != "ContainerRecreateRequest" or event.type == "DELETED":
            return
        self._enqueue((event.obj.metadata.namespace, event.obj.metadata.name))

    def _enqueue(self, key) -> None:
        with self._cond:
            self._queue.add(key)
            self._cond.notify()

    def _schedule_reap(self, key, delay: float) -> None:
        if self._thread is None:  # pull-mode (sync_once) drives its own TTL
            return
        timer = threading.Timer(delay, self._enqueue, args=(key,))
        timer.daemon = True
        with self._cond:
            if self._stop.is_set():
                return       # racing stop(): its snapshot already ran
            timer.start()    # start inside the guard, or a timer armed
            self._timers = [t for t in self._timers if t.is_alive()] \
                + [timer]    # between snapshot and append escapes cancel

    # ------------------------------------------------------------------ engine
    def _set_phase(self, req: ContainerRecreateRequest, phase: str,
                   message: str = "") -> bool:
        def mutate(r: ContainerRecreateRequest) -> None:
            r.status.phase = phase
            r.status.message = message
            if phase in (crr_api.PHASE_SUCCEEDED, crr_api.PHASE_FAILED):
                r.status.completion_time = utcnow()

        try:
            self.cluster.update_with_retry(
                ContainerRecreateRequest, req.metadata.namespace,
                req.metadata.name, mutate, subresource="status")
            return True
        except NotFoundError:
            return False  # operator collected/cancelled it mid-flight

    def _handle(self, req: ContainerRecreateRequest) -> None:
        ns = req.metadata.namespace
        if crr_api.finished(req):
            ttl = req.spec.ttl_seconds_after_finished
            done = req.status.completion_time
            if ttl is not None and done is not None:
                remaining = ttl - (utcnow() - done).total_seconds()
                if remaining <= 0:
                    try:
                        self.cluster.delete(ContainerRecreateRequest, ns,
                                            req.metadata.name)
                    except NotFoundError:
                        pass
                else:
                    # event-driven TTL: revisit this object at its expiry
                    # instead of rediscovering it by polling the collection
                    self._schedule_reap((ns, req.metadata.name),
                                        remaining + 0.05)
            return
        pod = self.cluster.try_get(Pod, ns, req.spec.pod_name)
        want_uid = req.metadata.labels.get(crr_api.LABEL_CRR_POD_UID)
        if pod is None or (want_uid and pod.metadata.uid != want_uid):
            self._set_phase(req, crr_api.PHASE_FAILED,
                            "target pod missing or replaced")
            return
        if self.node_name is not None and pod.spec.node_name != self.node_name:
            return  # another node's daemon owns this one
        if req.status.phase != crr_api.PHASE_RECREATING:
            if not self._set_phase(req, crr_api.PHASE_RECREATING):
                return
        try:
            # expect_uid re-verifies the incarnation INSIDE the retried
            # write: a pod deleted+recreated between the check above and
            # this call raises NotFound instead of forging the new pod
            self.runtime.recreate_containers(
                ns, req.spec.pod_name, req.spec.containers,
                expect_uid=want_uid or pod.metadata.uid)
        except NotFoundError:
            self._set_phase(req, crr_api.PHASE_FAILED,
                            "pod deleted or replaced mid-restart")
            return
        except (TimeoutError, CriError) as e:
            # runtime-level failure (dead containerd, kubelet not recreating):
            # Failed tells the operator to take the recreate fallback
            self._set_phase(req, crr_api.PHASE_FAILED,
                            f"runtime restart failed: {e}")
            return
        self.executed += 1
        self._set_phase(req, crr_api.PHASE_SUCCEEDED)

    def sync_once(self) -> None:
        """One pull-based pass (tests drive this directly for determinism)."""
        for req in self.cluster.list(ContainerRecreateRequest):
            try:
                self._handle(req)
            except (ConflictError, NotFoundError):
                pass  # racing the operator's collect/cancel — next pass settles

    def _loop(self) -> None:
        # One initial pass: the in-memory backend's watch delivers no cache
        # replay, and CRRs posted before start() must not wait for a resync.
        try:
            self.sync_once()
        # analyze: allow[silent-loss] startup pre-pass; the 5-min resync re-runs sync_once and CRR status surfaces real failures
        except Exception:  # noqa: BLE001 — the daemon must survive blips
            pass
        while not self._stop.is_set():
            with self._cond:
                if not self._queue:
                    self._cond.wait(timeout=self.resync_seconds)
                keys = list(self._queue)
                self._queue.clear()
            if self._stop.is_set():
                return
            if not keys:
                # resync heartbeat (5-minute default): catches a missed
                # event; NOT the steady-state path
                try:
                    self.sync_once()
                # analyze: allow[silent-loss] resync heartbeat blip; next heartbeat retries, CRR status is the durable signal
                except Exception:  # noqa: BLE001
                    pass
                continue
            for key in keys:
                try:
                    req = self.cluster.try_get(ContainerRecreateRequest, *key)
                    if req is not None:
                        self._handle(req)
                except (ConflictError, NotFoundError):
                    pass  # racing the operator's collect — resync settles it
                # analyze: allow[silent-loss] per-key blip; the key is re-queued by the next watch event or resync
                except Exception:  # noqa: BLE001 — the daemon must survive
                    pass
