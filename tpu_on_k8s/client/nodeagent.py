"""The CRR node agent — the OpenKruise-daemon role as a deployable actor.

``NodeAgentLoop`` watches ``ContainerRecreateRequest`` objects over its own
cluster connection and executes them against the node's container runtime —
which, from the API server's point of view, is the pod-status surface the
kubelet owns. With it running, the operator's ``CRRRestarter``
(`tpu_on_k8s/controller/failover.py`) never forges pod status; that
separation is what the reference buys by delegating in-place restarts to
kruise's node daemon (controllers/common/failover.go:210-307).

Deployed per node by ``config/nodeagent/daemonset.yaml`` (entrypoint:
``python -m tpu_on_k8s.main --node-agent-only --node-name $(NODE_NAME)``)
under its own ServiceAccount — the ONLY role RBAC grants ``pods/status``
writes to. The container runtime is an injectable seam: the default is the
``KubeletSim`` status-write surface (tests / local driver / simulated
clusters); a real-CRI shim implements the same ``recreate_containers``
signature.
"""
from __future__ import annotations

import threading
from typing import Optional

from tpu_on_k8s.api import crr as crr_api
from tpu_on_k8s.api.core import Pod, utcnow
from tpu_on_k8s.api.crr import ContainerRecreateRequest
from tpu_on_k8s.client.cluster import ConflictError, NotFoundError
from tpu_on_k8s.client.testing import KubeletSim


class NodeAgentLoop:
    """Honors ``ContainerRecreateRequest`` objects (the kruise-daemon side
    of reference failover.go:210-307):

    * a Pending CRR whose pod exists (and, for a node-scoped agent, is bound
      to this node) transitions ``Recreating`` → container restart →
      ``Succeeded`` + completion_time;
    * a CRR naming a missing pod — or one whose pod uid no longer matches
      the CRR's pod-uid label — is marked ``Failed`` (the operator falls
      back to delete+recreate on seeing it); the uid is ALSO re-verified
      inside the restart write itself, so a pod replaced mid-flight can
      never be forged to Running;
    * finished CRRs the operator never collected are reaped after
      ``ttl_seconds_after_finished`` (kruise's TTL reaper).

    ``node_name=None`` serves every node — one agent standing in for the
    whole DaemonSet, which is what single-process tests and the local
    driver run.
    """

    def __init__(self, cluster, *, node_name: Optional[str] = None,
                 poll_seconds: float = 0.02, runtime=None):
        self.cluster = cluster
        self.runtime = runtime if runtime is not None else KubeletSim(cluster)
        self.node_name = node_name
        self.poll_seconds = poll_seconds
        self.executed = 0  # restarts this agent performed (observability)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NodeAgentLoop":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-agent")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # ------------------------------------------------------------------ engine
    def _set_phase(self, req: ContainerRecreateRequest, phase: str,
                   message: str = "") -> bool:
        def mutate(r: ContainerRecreateRequest) -> None:
            r.status.phase = phase
            r.status.message = message
            if phase in (crr_api.PHASE_SUCCEEDED, crr_api.PHASE_FAILED):
                r.status.completion_time = utcnow()

        try:
            self.cluster.update_with_retry(
                ContainerRecreateRequest, req.metadata.namespace,
                req.metadata.name, mutate, subresource="status")
            return True
        except NotFoundError:
            return False  # operator collected/cancelled it mid-flight

    def _handle(self, req: ContainerRecreateRequest) -> None:
        ns = req.metadata.namespace
        if crr_api.finished(req):
            ttl = req.spec.ttl_seconds_after_finished
            done = req.status.completion_time
            if (ttl is not None and done is not None
                    and (utcnow() - done).total_seconds() >= ttl):
                try:
                    self.cluster.delete(ContainerRecreateRequest, ns,
                                        req.metadata.name)
                except NotFoundError:
                    pass
            return
        pod = self.cluster.try_get(Pod, ns, req.spec.pod_name)
        want_uid = req.metadata.labels.get(crr_api.LABEL_CRR_POD_UID)
        if pod is None or (want_uid and pod.metadata.uid != want_uid):
            self._set_phase(req, crr_api.PHASE_FAILED,
                            "target pod missing or replaced")
            return
        if self.node_name is not None and pod.spec.node_name != self.node_name:
            return  # another node's daemon owns this one
        if req.status.phase != crr_api.PHASE_RECREATING:
            if not self._set_phase(req, crr_api.PHASE_RECREATING):
                return
        try:
            # expect_uid re-verifies the incarnation INSIDE the retried
            # write: a pod deleted+recreated between the check above and
            # this call raises NotFound instead of forging the new pod
            self.runtime.recreate_containers(
                ns, req.spec.pod_name, req.spec.containers,
                expect_uid=want_uid or pod.metadata.uid)
        except NotFoundError:
            self._set_phase(req, crr_api.PHASE_FAILED,
                            "pod deleted or replaced mid-restart")
            return
        self.executed += 1
        self._set_phase(req, crr_api.PHASE_SUCCEEDED)

    def sync_once(self) -> None:
        """One pull-based pass (tests drive this directly for determinism)."""
        for req in self.cluster.list(ContainerRecreateRequest):
            try:
                self._handle(req)
            except (ConflictError, NotFoundError):
                pass  # racing the operator's collect/cancel — next pass settles

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001 — the daemon must survive blips
                pass
            self._stop.wait(self.poll_seconds)
