"""Cluster client layer (L2).

The reference generates a typed clientset + fake clientset from its CRDs
(/root/reference/client/, hack/update-codegen.sh). Here the same role is played by
a small hand-written client API (`ClusterClient`) with two backends:

* `InMemoryCluster` — a faithful in-process stand-in for the k8s API server
  (resource versions, conflicts, finalizers, deletionTimestamp, ownerRef cascade
  GC, label selection, watch events). This is both the test substrate (the
  reference's fake clientset analog) and the default runtime backend when no real
  cluster is configured.
* A real-cluster backend can implement the same `ClusterBackend` protocol over
  the k8s REST API; the controllers never know the difference.
"""

import tpu_on_k8s.api  # noqa: F401  — anchors the api→defaults→gang→client
                       # import cycle so `import tpu_on_k8s.client.*` works
                       # as the first framework import

from tpu_on_k8s.client.cluster import (
    ApiError,
    ConflictError,
    ConflictRetriesExhausted,
    InMemoryCluster,
    NotFoundError,
    WatchEvent,
)
from tpu_on_k8s.client.testing import KubeletLoop, KubeletSim
