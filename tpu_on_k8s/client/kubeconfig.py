"""Cluster connection config resolution (reference pkg/utils/kubeconfig).

The in-memory backend needs nothing; a live GKE backend resolves its API
server + credentials the standard way: ``$KUBECONFIG`` (or ``~/.kube/config``)
when running off-cluster, the mounted service-account when in-cluster
(reference kubeconfig.go:33-56). This module does the resolution without
importing any kubernetes client — the backend consumes the returned paths.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

IN_CLUSTER_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"
IN_CLUSTER_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


@dataclass(frozen=True)
class ClusterConfig:
    mode: str                       # "in-cluster" | "kubeconfig" | "none"
    kubeconfig_path: Optional[str] = None
    api_host: Optional[str] = None
    token_path: Optional[str] = None
    ca_path: Optional[str] = None


def server_url(cfg: ClusterConfig) -> Optional[str]:
    """Extract the API server URL a REST backend should dial.

    kubeconfig mode reads `clusters[0].cluster.server` (the current-context
    resolution the reference gets from clientcmd, kubeconfig.go:33-56);
    in-cluster mode uses the service-host env already captured in `cfg`.
    """
    if cfg.mode == "in-cluster":
        return cfg.api_host
    if cfg.mode == "kubeconfig" and cfg.kubeconfig_path:
        import yaml

        try:
            with open(cfg.kubeconfig_path) as f:
                doc = yaml.safe_load(f) or {}
        except OSError:
            return None
        current = doc.get("current-context")
        cluster_name = None
        for ctx in doc.get("contexts", []):
            if ctx.get("name") == current:
                cluster_name = ctx.get("context", {}).get("cluster")
                break
        for c in doc.get("clusters", []):
            if cluster_name is None or c.get("name") == cluster_name:
                return c.get("cluster", {}).get("server")
    return None


def resolve(env: Optional[dict] = None) -> ClusterConfig:
    """Kubeconfig env var → default path → in-cluster mount → none."""
    env = os.environ if env is None else env
    explicit = env.get("KUBECONFIG")
    if explicit and Path(explicit).exists():
        return ClusterConfig(mode="kubeconfig", kubeconfig_path=explicit)
    default = Path(env.get("HOME", "/root")) / ".kube" / "config"
    if default.exists():
        return ClusterConfig(mode="kubeconfig", kubeconfig_path=str(default))
    host = env.get("KUBERNETES_SERVICE_HOST")
    if host and Path(IN_CLUSTER_TOKEN).exists():
        port = env.get("KUBERNETES_SERVICE_PORT", "443")
        return ClusterConfig(mode="in-cluster", api_host=f"https://{host}:{port}",
                             token_path=IN_CLUSTER_TOKEN, ca_path=IN_CLUSTER_CA)
    return ClusterConfig(mode="none")
