"""Cluster connection config resolution (reference pkg/utils/kubeconfig).

The in-memory backend needs nothing; a live GKE backend resolves its API
server + credentials the standard way: ``$KUBECONFIG`` (or ``~/.kube/config``)
when running off-cluster, the mounted service-account when in-cluster
(reference kubeconfig.go:33-56). This module does the resolution without
importing any kubernetes client — the backend consumes the returned paths.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

IN_CLUSTER_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"
IN_CLUSTER_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


@dataclass(frozen=True)
class ClusterConfig:
    mode: str                       # "in-cluster" | "kubeconfig" | "none"
    kubeconfig_path: Optional[str] = None
    api_host: Optional[str] = None
    token_path: Optional[str] = None
    ca_path: Optional[str] = None
    token: Optional[str] = None           # inline bearer (kubeconfig `token`)
    client_cert_path: Optional[str] = None
    client_key_path: Optional[str] = None


def _load_doc(path: str) -> dict:
    import yaml

    try:
        with open(path) as f:
            return yaml.safe_load(f) or {}
    except OSError:
        return {}


def _current_context(doc: dict) -> dict:
    current = doc.get("current-context")
    for ctx in doc.get("contexts", []):
        if ctx.get("name") == current:
            return ctx.get("context", {}) or {}
    return {}


def server_url(cfg: ClusterConfig) -> Optional[str]:
    """Extract the API server URL a REST backend should dial.

    kubeconfig mode resolves the current context's cluster (the clientcmd
    resolution the reference gets for free, kubeconfig.go:33-56);
    in-cluster mode uses the service-host env already captured in `cfg`.
    """
    if cfg.mode == "in-cluster":
        return cfg.api_host
    if cfg.mode == "kubeconfig" and cfg.kubeconfig_path:
        doc = _load_doc(cfg.kubeconfig_path)
        cluster_name = _current_context(doc).get("cluster")
        for c in doc.get("clusters", []):
            if cluster_name is None or c.get("name") == cluster_name:
                return c.get("cluster", {}).get("server")
    return None


def _materialize(data_b64: str, tmpdir: str, name: str) -> str:
    """Write a kubeconfig inline `*-data` credential to a private file (the
    form python's ssl wants); 0600 like kubectl's own cache files."""
    import base64

    path = os.path.join(tmpdir, name)
    with open(path, "wb") as f:
        f.write(base64.b64decode(data_b64))
    os.chmod(path, 0o600)
    return path


def credentials(cfg: ClusterConfig,
                tmpdir: Optional[str] = None) -> ClusterConfig:
    """Resolve the current context's user/cluster credentials into the
    config: bearer token (`token` / `tokenFile`), client certificate
    (`client-certificate[-data]` + `client-key[-data]`, the mTLS path), and
    the cluster CA (`certificate-authority[-data]`). In-cluster mode is
    already complete (SA token + mounted CA). Inline `*-data` entries are
    materialized under ``tmpdir`` when given, else under a lazily-created
    private tempdir removed at process exit."""
    if cfg.mode != "kubeconfig" or not cfg.kubeconfig_path:
        return cfg
    doc = _load_doc(cfg.kubeconfig_path)
    ctx = _current_context(doc)
    user: dict = {}
    for u in doc.get("users", []):
        if ctx.get("user") is None or u.get("name") == ctx.get("user"):
            user = u.get("user", {}) or {}
            break
    cluster: dict = {}
    for c in doc.get("clusters", []):
        if ctx.get("cluster") is None or c.get("name") == ctx.get("cluster"):
            cluster = c.get("cluster", {}) or {}
            break

    state = {"tmpdir": tmpdir}

    def path_or_data(path_key: str, data_key: str, src: dict,
                     fname: str) -> Optional[str]:
        if src.get(path_key):
            return src[path_key]
        if src.get(data_key):
            if state["tmpdir"] is None:
                # lazy: only create (and clean up at exit) when an inline
                # credential actually needs a file on disk
                import atexit
                import shutil
                import tempfile

                state["tmpdir"] = tempfile.mkdtemp(prefix="tpu-on-k8s-creds-")
                atexit.register(shutil.rmtree, state["tmpdir"],
                                ignore_errors=True)
            return _materialize(src[data_key], state["tmpdir"], fname)
        return None

    return ClusterConfig(
        mode=cfg.mode, kubeconfig_path=cfg.kubeconfig_path,
        api_host=cfg.api_host,
        token=user.get("token"),
        token_path=user.get("tokenFile") or cfg.token_path,
        ca_path=path_or_data("certificate-authority",
                             "certificate-authority-data", cluster,
                             "ca.crt") or cfg.ca_path,
        client_cert_path=path_or_data("client-certificate",
                                      "client-certificate-data", user,
                                      "client.crt"),
        client_key_path=path_or_data("client-key", "client-key-data", user,
                                     "client.key"),
    )


def resolve(env: Optional[dict] = None) -> ClusterConfig:
    """Kubeconfig env var → default path → in-cluster mount → none."""
    env = os.environ if env is None else env
    explicit = env.get("KUBECONFIG")
    if explicit and Path(explicit).exists():
        return ClusterConfig(mode="kubeconfig", kubeconfig_path=explicit)
    default = Path(env.get("HOME", "/root")) / ".kube" / "config"
    if default.exists():
        return ClusterConfig(mode="kubeconfig", kubeconfig_path=str(default))
    host = env.get("KUBERNETES_SERVICE_HOST")
    if host and Path(IN_CLUSTER_TOKEN).exists():
        port = env.get("KUBERNETES_SERVICE_PORT", "443")
        return ClusterConfig(mode="in-cluster", api_host=f"https://{host}:{port}",
                             token_path=IN_CLUSTER_TOKEN, ca_path=IN_CLUSTER_CA)
    return ClusterConfig(mode="none")
