"""Real container-runtime executor for the CRR node agent (the CRI shim).

The reference's in-place restart terminates in kruise's node daemon doing an
actual CRI container kill; the kubelet then recreates the container under the
pod's restart policy and updates pod status itself
(/root/reference/controllers/common/failover.go:210-307 posts the CRR; the
kruise daemon executes it against the runtime). ``CriRuntime`` is that last
mile for this framework: it implements the same ``recreate_containers``
signature as the ``KubeletSim`` seam, but instead of writing pod status
through the API server it

1. resolves the pod's CRI sandbox by (namespace, name) and pins the pod
   incarnation via the ``io.kubernetes.pod.uid`` sandbox metadata
   (``expect_uid`` — a recreated same-name pod raises ``NotFoundError``,
   never a forged restart);
2. stops the target containers through the runtime (``crictl stop``), which
   is the CRI analog of kruise's kill;
3. waits READ-ONLY for the kubelet to bring up replacement containers (new
   container ids in ``CONTAINER_RUNNING`` state).

Pod status is therefore never written on this path — the kubelet owns it,
exactly the separation the CRR protocol exists to enforce.

The runtime is driven through ``crictl`` (present on any kubelet node; GKE
ships it) against the containerd socket rather than a hand-rolled gRPC
client: the image has no grpc stack, and crictl IS the stable CLI surface of
the CRI API. The command runner is injectable so tests drive the agent
against a recording fake-CRI double.
"""
from __future__ import annotations

import json
import subprocess
import time
from typing import Callable, Dict, List, Optional

from tpu_on_k8s.client.cluster import NotFoundError

DEFAULT_ENDPOINT = "unix:///run/containerd/containerd.sock"


class CriError(RuntimeError):
    """A runtime invocation failed (crictl non-zero exit / unreachable
    socket). The node agent surfaces it as CRR Failed — the operator's
    recreate fallback is the safe degraded path."""


def _subprocess_runner(argv: List[str], timeout: float) -> str:
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise CriError(f"{argv[0]}: {e}") from e
    if proc.returncode != 0:
        raise CriError(
            f"{' '.join(argv)} rc={proc.returncode}: {proc.stderr.strip()}")
    return proc.stdout


class CriRuntime:
    """``recreate_containers`` against a real node's container runtime.

    ``runner(argv, timeout) -> stdout`` is the execution seam (tests inject a
    recording double; production uses the subprocess runner above).
    """

    def __init__(self, *, crictl: str = "crictl",
                 endpoint: str = DEFAULT_ENDPOINT,
                 runner: Optional[Callable[[List[str], float], str]] = None,
                 stop_timeout_seconds: int = 30,
                 wait_seconds: float = 60.0, poll_seconds: float = 0.5):
        self.crictl = crictl
        self.endpoint = endpoint
        self.runner = runner if runner is not None else _subprocess_runner
        self.stop_timeout_seconds = stop_timeout_seconds
        self.wait_seconds = wait_seconds
        self.poll_seconds = poll_seconds

    # ------------------------------------------------------------ CRI reads
    def _run(self, *args: str) -> str:
        argv = [self.crictl, "--runtime-endpoint", self.endpoint, *args]
        # command timeout: the stop itself may legitimately take the full
        # grace period, plus slack for the runtime to respond
        return self.runner(argv, self.stop_timeout_seconds + 30.0)

    def _json(self, *args: str) -> dict:
        out = self._run(*args)
        try:
            return json.loads(out) if out.strip() else {}
        except json.JSONDecodeError as e:
            raise CriError(f"unparseable crictl output: {out[:200]!r}") from e

    def _find_sandbox(self, namespace: str, name: str,
                      expect_uid: Optional[str]) -> str:
        data = self._json("pods", "--name", name, "--namespace", namespace,
                          "--state", "ready", "-o", "json")
        for item in data.get("items", []):
            meta = item.get("metadata", {})
            if meta.get("name") != name or meta.get("namespace") != namespace:
                continue  # crictl name filters are substring matches
            if expect_uid is not None and meta.get("uid") != expect_uid:
                raise NotFoundError(
                    f"pod {namespace}/{name} incarnation changed "
                    f"(sandbox uid {meta.get('uid')} != {expect_uid})")
            return item["id"]
        raise NotFoundError(
            f"no ready CRI sandbox for pod {namespace}/{name} on this node")

    def _containers(self, sandbox_id: str) -> List[dict]:
        data = self._json("ps", "-a", "--pod", sandbox_id, "-o", "json")
        return data.get("containers", [])

    # --------------------------------------------------------------- restart
    def recreate_containers(self, namespace: str, name: str,
                            containers: Optional[list] = None,
                            expect_uid: Optional[str] = None) -> None:
        """Stop the named containers (all, if empty) and wait for the kubelet
        to recreate them. Raises ``NotFoundError`` when the pod/sandbox is
        gone or its uid changed, ``TimeoutError`` when the kubelet does not
        bring replacements up in time, ``CriError`` on runtime failures."""
        sandbox = self._find_sandbox(namespace, name, expect_uid)
        wanted = set(containers or [])
        # Pick the LATEST attempt per container name: `ps -a` also returns
        # exited earlier attempts of the same container, and letting one of
        # those shadow the live id would make `stop` a no-op while the wait
        # loop immediately blesses the still-running current container as
        # the "replacement" — a forged restart.
        latest: Dict[str, dict] = {}
        for c in self._containers(sandbox):
            cname = c.get("metadata", {}).get("name")
            if wanted and cname not in wanted:
                continue
            attempt = c.get("metadata", {}).get("attempt", 0)
            if (cname not in latest
                    or attempt > latest[cname]["metadata"].get("attempt", 0)):
                latest[cname] = c
        missing = wanted - set(latest)
        if missing:
            raise CriError(
                f"containers {sorted(missing)} not found in pod "
                f"{namespace}/{name}")
        if not latest:
            raise CriError(f"pod {namespace}/{name} has no containers")
        old_ids: Dict[str, str] = {n: c["id"] for n, c in latest.items()}
        for c in latest.values():
            if c.get("state") != "CONTAINER_RUNNING":
                continue  # already stopped/crashed — kubelet recreates it
            try:
                self._run("stop", "--timeout",
                          str(self.stop_timeout_seconds), c["id"])
            except CriError as e:
                # a container that exited between list and stop is fine — the
                # kubelet will recreate it either way
                if "not found" not in str(e).lower():
                    raise
        deadline = time.monotonic() + self.wait_seconds
        while True:
            fresh = {}
            for c in self._containers(sandbox):
                cname = c.get("metadata", {}).get("name")
                if (cname in old_ids and c["id"] != old_ids[cname]
                        and c.get("state") == "CONTAINER_RUNNING"):
                    fresh[cname] = c["id"]
            if set(fresh) == set(old_ids):
                return
            if time.monotonic() >= deadline:
                waiting = sorted(set(old_ids) - set(fresh))
                raise TimeoutError(
                    f"kubelet did not recreate containers {waiting} of pod "
                    f"{namespace}/{name} within {self.wait_seconds}s")
            time.sleep(self.poll_seconds)
