"""RestCluster: the typed REST client — same surface as InMemoryCluster.

The analog of the reference's generated typed clientset
(/root/reference/client/clientset/versioned/clientset.go) plus the informer
layer (client/informers/externalversions/factory.go): every InMemoryCluster
method (create/get/list/update/patch_meta/delete/watch/status-subresource/
pod-log/events) is implemented by speaking the k8s-style REST protocol of
`client/apiserver.py` over plain HTTP. Controllers are backend-agnostic —
`main.py --cluster-backend rest --api-server URL` swaps this in with no
controller changes (VERDICT round 1, missing #1).

Watch design: one streaming GET per registered kind (the informer-per-type
model, not a fictional all-resource watch). `watch(callback)` blocks until
every stream has delivered its initial BOOKMARK, so events emitted after it
returns are guaranteed to be observed. Errors map from typed Status bodies:
404→NotFoundError, 409 AlreadyExists/Conflict→the matching exception — the
same failure modes the controllers face in-memory.
"""
from __future__ import annotations

import json
import ssl
import threading
from http.client import HTTPConnection, HTTPSConnection
from typing import Any, Callable, Dict, Iterable, List, Optional
from urllib.parse import quote, urlparse

from tpu_on_k8s.client import resources
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    NotFoundError,
    WatchEvent,
)
from tpu_on_k8s.utils import serde
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("restclient")


def _raise_for_status(code: int, body: bytes) -> None:
    try:
        status = json.loads(body or b"{}")
    except json.JSONDecodeError:
        status = {}
    reason = status.get("reason", "")
    message = status.get("message", body.decode(errors="replace"))
    if code == 404 or reason == "NotFound":
        raise NotFoundError(message)
    if reason == "AlreadyExists":
        raise AlreadyExistsError(message)
    if code == 409 or reason == "Conflict":
        raise ConflictError(message)
    raise ApiError(f"HTTP {code}: {message}")


class RestCluster:
    """k8s REST client with the InMemoryCluster surface (duck-typed)."""

    def __init__(self, base_url: str, timeout: float = 10.0,
                 token_path: Optional[str] = None,
                 ca_path: Optional[str] = None) -> None:
        parsed = urlparse(base_url)
        if parsed.scheme not in ("http", "https", ""):
            raise ValueError(f"unsupported scheme {parsed.scheme!r}")
        self.tls = parsed.scheme == "https"
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or (443 if self.tls else 80)
        self.timeout = timeout
        self._token_path = token_path  # re-read per request: SA tokens rotate
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self.tls:
            self._ssl_ctx = (ssl.create_default_context(cafile=ca_path)
                             if ca_path else ssl.create_default_context())
        self._local = threading.local()
        self._watch_lock = threading.Lock()
        self._watch_callbacks: List[Callable[[WatchEvent], None]] = []
        self._watch_threads: List[threading.Thread] = []
        self._watch_stop = threading.Event()

    # ------------------------------------------------------------------ plumbing
    def _new_conn(self, timeout: Optional[float]) -> HTTPConnection:
        if self.tls:
            return HTTPSConnection(self.host, self.port, timeout=timeout,
                                   context=self._ssl_ctx)
        return HTTPConnection(self.host, self.port, timeout=timeout)

    def _conn(self) -> HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._new_conn(self.timeout)
            self._local.conn = conn
        return conn

    def _headers(self, has_payload: bool) -> Dict[str, str]:
        headers = {"Content-Type": "application/json"} if has_payload else {}
        if self._token_path:
            try:
                with open(self._token_path) as f:
                    headers["Authorization"] = f"Bearer {f.read().strip()}"
            except OSError:
                pass
        return headers

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Any:
        payload = json.dumps(body).encode() if body is not None else None
        headers = self._headers(payload is not None)
        for attempt in (0, 1):  # one retry on a stale keep-alive connection
            conn = self._conn()
            try:
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                break
            except (ConnectionError, OSError):
                self._local.conn = None
                if attempt:
                    raise
        if resp.status >= 400:
            _raise_for_status(resp.status, data)
        ctype = resp.headers.get("Content-Type", "")
        if ctype.startswith("text/plain"):
            return data.decode()
        return json.loads(data or b"{}")

    # --------------------------------------------------------------------- CRUD
    def create(self, obj: Any) -> Any:
        rt = resources.by_class(type(obj))
        ns = obj.metadata.namespace or "default"
        data = self._request("POST", rt.collection_path(ns),
                             serde.to_dict(obj, drop_none=False))
        return serde.from_dict(rt.cls, data)

    def get(self, cls: type, namespace: str, name: str) -> Any:
        rt = resources.by_class(cls)
        data = self._request("GET", rt.item_path(namespace, quote(name)))
        return serde.from_dict(rt.cls, data)

    def try_get(self, cls: type, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(cls, namespace, name)
        except NotFoundError:
            return None

    def list(self, cls: type, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        rt = resources.by_class(cls)
        path = (rt.collection_path(namespace) if namespace is not None
                else rt.all_namespaces_path())
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            path += f"?labelSelector={quote(sel)}"
        data = self._request("GET", path)
        return [serde.from_dict(rt.cls, item) for item in data.get("items", [])]

    def update(self, obj: Any, *, subresource: str = "") -> Any:
        rt = resources.by_class(type(obj))
        path = rt.item_path(obj.metadata.namespace, quote(obj.metadata.name))
        if subresource:
            path += f"/{subresource}"
        data = self._request("PUT", path, serde.to_dict(obj, drop_none=False))
        return serde.from_dict(rt.cls, data)

    def patch_meta(self, cls: type, namespace: str, name: str, *,
                   labels: Optional[Dict[str, Optional[str]]] = None,
                   annotations: Optional[Dict[str, Optional[str]]] = None,
                   add_finalizers: Iterable[str] = (),
                   remove_finalizers: Iterable[str] = ()) -> Any:
        rt = resources.by_class(cls)
        meta: Dict[str, Any] = {}
        if labels:
            meta["labels"] = labels
        if annotations:
            meta["annotations"] = annotations
        if add_finalizers:
            meta["$addFinalizers"] = list(add_finalizers)
        if remove_finalizers:
            meta["$removeFinalizers"] = list(remove_finalizers)
        data = self._request("PATCH", rt.item_path(namespace, quote(name)),
                             {"metadata": meta})
        return serde.from_dict(rt.cls, data)

    def delete(self, cls: type, namespace: str, name: str) -> None:
        rt = resources.by_class(cls)
        self._request("DELETE", rt.item_path(namespace, quote(name)))

    def update_with_retry(self, cls: type, namespace: str, name: str,
                          mutate: Callable[[Any], None], *,
                          subresource: str = "", attempts: int = 5) -> Any:
        last: Optional[Exception] = None
        for _ in range(attempts):
            obj = self.get(cls, namespace, name)
            mutate(obj)
            try:
                return self.update(obj, subresource=subresource)
            except ConflictError as e:
                last = e
        raise last  # type: ignore[misc]

    # ----------------------------------------------------------- events & logs
    def record_event(self, obj: Any, etype: str, reason: str,
                     message: str) -> None:
        ns = obj.metadata.namespace or "default"
        self._request("POST", f"/api/v1/namespaces/{ns}/events", {
            "involvedObject": {"namespace": ns, "name": obj.metadata.name},
            "type": etype, "reason": reason, "message": message})

    def list_events(self, namespace: str = "default") -> List[tuple]:
        data = self._request("GET", f"/api/v1/namespaces/{namespace}/events")
        return [tuple(e) for e in data.get("items", [])]

    @property
    def events(self) -> List[tuple]:
        """Parity with InMemoryCluster.events for assertions/tests."""
        return self.list_events()

    def append_pod_log(self, namespace: str, name: str, line: str) -> None:
        self._request("POST",
                      f"/api/v1/namespaces/{namespace}/pods/{quote(name)}/log",
                      {"line": line})

    def read_pod_log(self, namespace: str, name: str, *,
                     tail: int = 0) -> List[str]:
        path = f"/api/v1/namespaces/{namespace}/pods/{quote(name)}/log"
        if tail:
            path += f"?tailLines={tail}"
        text = self._request("GET", path)
        return text.split("\n") if text else []

    # -------------------------------------------------------------------- watch
    def watch(self, callback: Callable[[WatchEvent], None]) -> None:
        """Register a callback for all kinds. First registration opens one
        streaming watch per registered resource type and BLOCKS until every
        stream is live (initial BOOKMARK observed)."""
        with self._watch_lock:
            self._watch_callbacks.append(callback)
            if self._watch_threads:
                return
            ready: List[threading.Event] = []
            for rt in resources.all_types():
                ev = threading.Event()
                ready.append(ev)
                t = threading.Thread(target=self._watch_loop, args=(rt, ev),
                                     daemon=True, name=f"watch-{rt.plural}")
                t.start()
                self._watch_threads.append(t)
        for ev in ready:
            if not ev.wait(timeout=10):
                raise ApiError("watch stream failed to establish")

    def _watch_loop(self, rt: resources.ResourceType,
                    ready: threading.Event) -> None:
        conn = self._new_conn(None)  # no timeout: long-lived stream
        try:
            conn.request("GET", rt.all_namespaces_path() + "?watch=true",
                         headers=self._headers(False))
            resp = conn.getresponse()
            while not self._watch_stop.is_set():
                line = resp.readline()
                if not line:
                    break  # server closed the stream
                line = line.strip()
                if not line:
                    continue
                msg = json.loads(line)
                if msg.get("type") == "BOOKMARK":
                    ready.set()
                    continue
                obj = serde.from_dict(rt.cls, msg["object"])
                event = WatchEvent(msg["type"], rt.kind, obj)
                with self._watch_lock:
                    callbacks = list(self._watch_callbacks)
                for cb in callbacks:
                    try:
                        cb(event)
                    except Exception:
                        _log.exception("watch callback failed",
                                       extra={"kv": {"kind": rt.kind}})
        except (ConnectionError, OSError):
            pass
        finally:
            ready.set()  # never leave watch() blocked on a dead stream
            conn.close()

    def close(self) -> None:
        self._watch_stop.set()
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
