"""RestCluster: the typed REST client — same surface as InMemoryCluster.

The analog of the reference's generated typed clientset
(/root/reference/client/clientset/versioned/clientset.go) plus the informer
layer (client/informers/externalversions/factory.go): every InMemoryCluster
method (create/get/list/update/patch_meta/delete/watch/status-subresource/
pod-log/events) is implemented by speaking conformant Kubernetes REST —
camelCase JSON, real resource scoping, RFC 7386 merge-patch with
resourceVersion preconditions for metadata/finalizer changes (the patch
dialect a real apiserver accepts for CRDs; the reference builds the same
payloads via pkg/utils/patch/patch.go:66-96), and core/v1 Event objects.
Controllers are backend-agnostic — `main.py --cluster-backend rest
--api-server URL` swaps this in with no controller changes.

Watch design (the real informer contract, reference main.go:77-83):
one list-then-watch loop per registered kind. Each loop LISTs the collection
(capturing the list's ``metadata.resourceVersion``), delivers every item as a
synthetic ADDED event (initial sync / re-list replay — level-triggered
consumers treat duplicates as no-ops), then opens
``?watch=true&resourceVersion=N&allowWatchBookmarks=true`` and follows the
stream. A dropped stream reconnects from the last observed revision with
backoff; ``410 Gone``/``Expired`` ERROR frames trigger a full re-list.
BOOKMARK frames are consumed when present but never required.
`watch(callback)` blocks until every kind's initial list has been delivered,
so no pre-existing object is missed. Errors map from typed Status bodies:
404→NotFoundError, 409 AlreadyExists/Conflict→the matching exception — the
same failure modes the controllers face in-memory.
"""
from __future__ import annotations

import json
import random
import ssl
import threading
import time
import uuid
from http.client import HTTPConnection, HTTPSConnection
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple
from urllib.parse import quote, urlparse

from tpu_on_k8s import chaos
from tpu_on_k8s.api.core import Event, ObjectReference, utcnow
from tpu_on_k8s.client import resources
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    ExpiredError,
    NotFoundError,
    WatchEvent,
    run_conflict_retries,
)
from tpu_on_k8s.utils import serde
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("restclient")


def _raise_for_status(code: int, body: bytes) -> None:
    try:
        status = json.loads(body or b"{}")
    except json.JSONDecodeError:
        status = {}
    reason = status.get("reason", "")
    message = status.get("message", body.decode(errors="replace"))
    if code == 404 or reason == "NotFound":
        raise NotFoundError(message)
    if reason == "AlreadyExists":
        raise AlreadyExistsError(message)
    if code == 409 or reason == "Conflict":
        raise ConflictError(message)
    if code == 410 or reason == "Expired":
        raise ExpiredError(message)
    raise ApiError(f"HTTP {code}: {message}")


def _wire(obj: Any) -> Dict[str, Any]:
    return serde.to_dict(obj, drop_none=False, wire=True)


class RestCluster:
    """k8s REST client with the InMemoryCluster surface (duck-typed)."""

    #: reconnect backoff bounds for dropped watch streams
    WATCH_BACKOFF_INITIAL = 0.2
    WATCH_BACKOFF_MAX = 5.0

    def __init__(self, base_url: str, timeout: float = 10.0,
                 token_path: Optional[str] = None,
                 ca_path: Optional[str] = None,
                 token: Optional[str] = None,
                 client_cert_path: Optional[str] = None,
                 client_key_path: Optional[str] = None) -> None:
        """``token_path`` (re-read per request — SA tokens rotate) or inline
        ``token`` for bearer auth; ``client_cert_path``/``client_key_path``
        for mTLS client-certificate auth (the kubeconfig
        ``client-certificate``/``client-key`` user entries,
        reference pkg/utils/kubeconfig/kubeconfig.go:33-56)."""
        parsed = urlparse(base_url)
        if parsed.scheme not in ("http", "https", ""):
            raise ValueError(f"unsupported scheme {parsed.scheme!r}")
        self.tls = parsed.scheme == "https"
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or (443 if self.tls else 80)
        self.timeout = timeout
        self._token_path = token_path  # re-read per request: SA tokens rotate
        self._token = token
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self.tls:
            self._ssl_ctx = (ssl.create_default_context(cafile=ca_path)
                             if ca_path else ssl.create_default_context())
            if client_cert_path:
                self._ssl_ctx.load_cert_chain(client_cert_path,
                                              client_key_path)
        self._local = threading.local()
        #: optional JobMetrics sink (conflict-retry counter); the operator
        #: wires its own instance in, library callers may leave None
        self.metrics = None
        # Decorrelated-jitter state for watch reconnects. Entropy-seeded by
        # default (each process jitters differently — that is the point);
        # tests needing determinism reseed ``_backoff_rng`` directly.
        self._backoff_rng = random.Random()
        self._watch_lock = threading.Lock()
        self._watch_callbacks: List[Callable[[WatchEvent], None]] = []
        self._watch_threads: List[threading.Thread] = []
        self._watch_running: set = set()  # kinds with a live informer loop
        self._watch_stop = threading.Event()
        # informer cache: kind → {(ns, name): obj}. Source of truth for
        # synthetic DELETED on re-list and for initial-sync replay to
        # callbacks registered after the loops started.
        self._known: Dict[str, Dict[Tuple[str, str], Any]] = {}

    # ------------------------------------------------------------------ plumbing
    def _new_conn(self, timeout: Optional[float]) -> HTTPConnection:
        if self.tls:
            return HTTPSConnection(self.host, self.port, timeout=timeout,
                                   context=self._ssl_ctx)
        return HTTPConnection(self.host, self.port, timeout=timeout)

    def _conn(self) -> HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._new_conn(self.timeout)
            self._local.conn = conn
        return conn

    def _headers(self, content_type: Optional[str]) -> Dict[str, str]:
        headers = {"Content-Type": content_type} if content_type else {}
        # client-go precedence: an inline token wins over tokenFile (the
        # file is not even read then); with only a tokenFile it is re-read
        # per request (SA tokens rotate), and an unreadable file degrades
        # to no auth — the server's 401 is the actionable signal.
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        elif self._token_path:
            try:
                with open(self._token_path) as f:
                    file_token = f.read().strip()
            except OSError:
                file_token = None
            if file_token:
                headers["Authorization"] = f"Bearer {file_token}"
        return headers

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json") -> Any:
        payload = json.dumps(body).encode() if body is not None else None
        headers = self._headers(content_type if payload is not None else None)
        for attempt in (0, 1):  # one retry on a stale keep-alive connection
            fault = chaos.fire(chaos.SITE_REST_REQUEST, method=method,
                               path=path, attempt=attempt)
            if fault is not None:
                exc = fault.to_exception()
                if isinstance(exc, OSError) and not isinstance(exc, ApiError):
                    # connection-level fault: takes the real stale-connection
                    # path (drop the conn, retry once) — a single injected
                    # reset is absorbed exactly like a real keep-alive reset
                    self._local.conn = None
                    if attempt:
                        raise exc
                    continue
                raise exc  # HTTP-level fault (5xx/409): surfaces typed
            conn = self._conn()
            try:
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                break
            except (ConnectionError, OSError):
                self._local.conn = None
                if attempt:
                    raise
        if resp.status >= 400:
            _raise_for_status(resp.status, data)
        ctype = resp.headers.get("Content-Type", "")
        if ctype.startswith("text/plain"):
            return data.decode()
        return json.loads(data or b"{}")

    # --------------------------------------------------------------------- CRUD
    def create(self, obj: Any) -> Any:
        rt = resources.by_class(type(obj))
        ns = obj.metadata.namespace or "default"
        data = self._request("POST", rt.collection_path(ns), _wire(obj))
        return serde.from_dict(rt.cls, data)

    def get(self, cls: type, namespace: str, name: str) -> Any:
        rt = resources.by_class(cls)
        data = self._request("GET", rt.item_path(namespace, quote(name)))
        return serde.from_dict(rt.cls, data)

    def try_get(self, cls: type, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(cls, namespace, name)
        except NotFoundError:
            return None

    def list(self, cls: type, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        items, _ = self._list_with_rv(resources.by_class(cls), namespace,
                                      label_selector)
        return items

    def _list_with_rv(self, rt: resources.ResourceType,
                      namespace: Optional[str] = None,
                      label_selector: Optional[Dict[str, str]] = None,
                      ) -> Tuple[List[Any], int]:
        """List + the collection's ``metadata.resourceVersion`` — the revision
        a subsequent watch resumes from (list-then-watch, no event gap)."""
        path = (rt.collection_path(namespace)
                if namespace is not None and rt.namespaced
                else rt.all_namespaces_path())
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            path += f"?labelSelector={quote(sel)}"
        data = self._request("GET", path)
        rv = int(data.get("metadata", {}).get("resourceVersion", 0) or 0)
        return ([serde.from_dict(rt.cls, item)
                 for item in data.get("items", [])], rv)

    def update(self, obj: Any, *, subresource: str = "") -> Any:
        rt = resources.by_class(type(obj))
        path = rt.item_path(obj.metadata.namespace, quote(obj.metadata.name))
        if subresource:
            path += f"/{subresource}"
        data = self._request("PUT", path, _wire(obj))
        return serde.from_dict(rt.cls, data)

    def patch_meta(self, cls: type, namespace: str, name: str, *,
                   labels: Optional[Dict[str, Optional[str]]] = None,
                   annotations: Optional[Dict[str, Optional[str]]] = None,
                   add_finalizers: Iterable[str] = (),
                   remove_finalizers: Iterable[str] = ()) -> Any:
        """Metadata patch via standard JSON merge-patch (RFC 7386).

        Labels/annotations merge directly (null deletes a key). Finalizers
        are a list — merge-patch replaces lists wholesale — so finalizer
        edits do read-modify-write with a ``metadata.resourceVersion``
        precondition and retry on conflict, exactly how conformant
        controllers edit finalizers on CRDs.
        """
        rt = resources.by_class(cls)
        add_f, remove_f = list(add_finalizers), list(remove_finalizers)
        meta: Dict[str, Any] = {}
        if labels:
            meta["labels"] = labels
        if annotations:
            meta["annotations"] = annotations
        if not add_f and not remove_f:
            data = self._request(
                "PATCH", rt.item_path(namespace, quote(name)),
                {"metadata": meta},
                content_type="application/merge-patch+json")
            return serde.from_dict(rt.cls, data)
        def attempt() -> Any:
            cur = self.get(cls, namespace, name)
            fins = [f for f in cur.metadata.finalizers if f not in remove_f]
            fins += [f for f in add_f if f not in fins]
            patch_meta = dict(meta)
            patch_meta["finalizers"] = fins
            # opaque string on the wire, like every k8s resourceVersion
            patch_meta["resourceVersion"] = str(cur.metadata.resource_version)
            data = self._request(
                "PATCH", rt.item_path(namespace, quote(name)),
                {"metadata": patch_meta},
                content_type="application/merge-patch+json")
            return serde.from_dict(rt.cls, data)

        return run_conflict_retries(5, attempt,
                                    f"metadata patch of {namespace}/{name}",
                                    self.metrics)

    def delete(self, cls: type, namespace: str, name: str) -> None:
        rt = resources.by_class(cls)
        self._request("DELETE", rt.item_path(namespace, quote(name)))

    def update_with_retry(self, cls: type, namespace: str, name: str,
                          mutate: Callable[[Any], None], *,
                          subresource: str = "", attempts: int = 5) -> Any:
        """Read-mutate-write, BOUNDED: past ``attempts`` sustained 409s it
        raises the typed ``ConflictRetriesExhausted`` (a ``ConflictError``
        subclass, so existing handlers keep working) instead of spinning —
        under a chaos schedule injecting permanent conflicts an unbounded
        loop is a livelock. Every retried conflict feeds the
        ``conflict_retries`` counter when ``self.metrics`` is wired."""
        def attempt() -> Any:
            obj = self.get(cls, namespace, name)
            mutate(obj)
            return self.update(obj, subresource=subresource)

        return run_conflict_retries(attempts, attempt,
                                    f"update of {namespace}/{name}",
                                    self.metrics)

    # ----------------------------------------------------------- events & logs
    def record_event(self, obj: Any, etype: str, reason: str,
                     message: str) -> None:
        """POST a real core/v1 Event (what record.EventRecorder emits)."""
        ns = obj.metadata.namespace or "default"
        now = utcnow()
        ev = Event(
            involved_object=ObjectReference(
                api_version=getattr(obj, "api_version", ""), kind=obj.kind,
                namespace=ns, name=obj.metadata.name, uid=obj.metadata.uid),
            type=etype, reason=reason, message=message,
            first_timestamp=now, last_timestamp=now)
        ev.metadata.namespace = ns
        # monotonic_ns is process-local (manager and scheduler can collide)
        # and may be coarse — salt with randomness and retry the residual race
        for attempt in range(3):
            ev.metadata.name = (f"{obj.metadata.name}."
                                f"{time.monotonic_ns():x}."
                                f"{uuid.uuid4().hex[:6]}")
            try:
                self.create(ev)
                return
            except AlreadyExistsError:
                if attempt == 2:  # never drop an event silently
                    raise

    def list_events(self, namespace: Optional[str] = None) -> List[tuple]:
        """Events as tuples; ``namespace=None`` spans all namespaces (the
        InMemoryCluster.events parity surface is cluster-wide)."""
        evs = self.list(Event, namespace)
        evs.sort(key=lambda e: e.metadata.resource_version)
        return [(f"{e.involved_object.namespace}/{e.involved_object.name}",
                 e.type, e.reason, e.message) for e in evs]

    @property
    def events(self) -> List[tuple]:
        """Parity with InMemoryCluster.events for assertions/tests."""
        return self.list_events()

    def read_pod_log(self, namespace: str, name: str, *,
                     tail: int = 0) -> List[str]:
        path = f"/api/v1/namespaces/{namespace}/pods/{quote(name)}/log"
        if tail:
            path += f"?tailLines={tail}"
        text = self._request("GET", path)
        return text.split("\n") if text else []

    # -------------------------------------------------------------------- watch
    def watch(self, callback: Callable[[WatchEvent], None],
              kinds: Optional[Iterable[str]] = None) -> None:
        """Register a callback and ensure a list-then-watch informer loop is
        running for each requested kind (all registered kinds when ``kinds``
        is None) — a node-scoped actor that only cares about one kind (the
        CRR node agent) runs ONE stream, not one per resource type. BLOCKS
        until every newly started loop has delivered its initial list. If
        loops for the requested kinds already run, the informer cache is
        replayed to the new callback as synthetic ADDED events (informer
        AddEventHandler semantics), so every controller — not just the
        first — observes pre-existing objects. Callbacks receive events for
        every kind any registration requested; filter by ``event.kind``."""
        wanted = [rt for rt in resources.all_types()
                  if kinds is None or rt.kind in set(kinds)]
        with self._watch_lock:
            snapshot = [obj for cache in self._known.values()
                        for obj in cache.values()]
            already_running = bool(self._watch_running)
            self._watch_callbacks.append(callback)
            ready: List[threading.Event] = []
            for rt in wanted:
                if rt.kind in self._watch_running:
                    continue
                self._watch_running.add(rt.kind)
                ev = threading.Event()
                ready.append(ev)
                t = threading.Thread(target=self._watch_loop,
                                     args=(rt, ev), daemon=True,
                                     name=f"watch-{rt.plural}")
                t.start()
                self._watch_threads.append(t)
        if already_running:
            # Replay the informer cache to the newcomer, outside the lock
            # (callbacks may re-enter the client). A concurrent live event
            # may duplicate — level-triggered consumers treat duplicates as
            # no-ops.
            for obj in snapshot:
                try:
                    callback(WatchEvent("ADDED", obj.kind, obj))
                except Exception:
                    if self.metrics is not None:
                        self.metrics.error()
                    _log.exception("watch callback failed on sync replay")
        for ev in ready:
            if not ev.wait(timeout=30):
                raise ApiError("watch stream failed to establish")

    def _dispatch(self, event: WatchEvent) -> None:
        key = (event.obj.metadata.namespace, event.obj.metadata.name)
        with self._watch_lock:
            cache = self._known.setdefault(event.kind, {})
            if event.type == "DELETED":
                cache.pop(key, None)
            else:
                cache[key] = event.obj
            callbacks = list(self._watch_callbacks)
        for cb in callbacks:
            try:
                cb(event)
            except Exception:
                if self.metrics is not None:
                    self.metrics.error()
                _log.exception("watch callback failed",
                               extra={"kv": {"kind": event.kind}})

    def _sync(self, rt: resources.ResourceType) -> int:
        """Initial list (or re-list): deliver every current object as ADDED,
        synthesize DELETED for cached objects that vanished during the outage
        (the informer's DeletedFinalStateUnknown replay — without it a job
        deleted while the stream was down would leak controller bookkeeping
        forever), and return the list revision."""
        items, rv = self._list_with_rv(rt)
        listed = {(o.metadata.namespace, o.metadata.name) for o in items}
        with self._watch_lock:
            gone = [obj for key, obj in self._known.get(rt.kind, {}).items()
                    if key not in listed]
        for obj in gone:
            self._dispatch(WatchEvent("DELETED", rt.kind, obj))
        for obj in items:
            self._dispatch(WatchEvent("ADDED", rt.kind, obj))
        return rv

    def _next_backoff(self, prev: float) -> float:
        """Decorrelated-jitter reconnect backoff (AWS architecture blog's
        "decorrelated jitter"): ``uniform(initial, 3*prev)`` capped at the
        max. Plain exponential backoff resynchronizes every watcher that an
        API-server blip disconnected at the same instant — they all retry
        in lockstep at t+0.2, t+0.6, ... and the thundering herd re-kills
        the server; jitter spreads the herd across the whole window."""
        return min(self.WATCH_BACKOFF_MAX,
                   self._backoff_rng.uniform(self.WATCH_BACKOFF_INITIAL,
                                             prev * 3.0))

    def _watch_loop(self, rt: resources.ResourceType,
                    ready: threading.Event) -> None:
        """List-then-watch with resume and recovery (informer semantics):
        dropped stream → reconnect from the last seen revision with
        decorrelated-jitter backoff; 410 Expired → full re-list. Never goes
        silently deaf."""
        rv: Optional[int] = None
        backoff = self.WATCH_BACKOFF_INITIAL
        while not self._watch_stop.is_set():
            conn = None
            try:
                if rv is None:
                    rv = self._sync(rt)
                    ready.set()
                fault = chaos.fire(chaos.SITE_REST_WATCH_CONNECT,
                                   kind=rt.kind)
                if fault is not None:
                    raise fault.to_exception()
                conn = self._new_conn(None)  # no timeout: long-lived stream
                path = (rt.all_namespaces_path()
                        + f"?watch=true&resourceVersion={rv}"
                        + "&allowWatchBookmarks=true")
                conn.request("GET", path, headers=self._headers(None))
                resp = conn.getresponse()
                if resp.status == 410:
                    _log.warning("watch expired; re-listing",
                                 extra={"kv": {"kind": rt.kind, "rv": rv}})
                    rv = None
                    continue
                if resp.status >= 400:
                    _raise_for_status(resp.status, resp.read())
                while not self._watch_stop.is_set():
                    line = resp.readline()
                    if not line:
                        break  # server closed the stream → reconnect from rv
                    line = line.strip()
                    if not line:
                        continue
                    msg = json.loads(line)
                    mtype = msg.get("type")
                    if mtype == "BOOKMARK":
                        # optional: only advances the resume revision
                        raw = (msg.get("object", {}).get("metadata", {})
                               .get("resourceVersion"))
                        if raw is not None:
                            rv = int(raw)
                        continue
                    if mtype == "ERROR":
                        code = msg.get("object", {}).get("code")
                        if code == 410:
                            rv = None  # window lost → re-list
                        break
                    obj = serde.from_dict(rt.cls, msg["object"])
                    rv = obj.metadata.resource_version
                    self._dispatch(WatchEvent(mtype, rt.kind, obj))
                    backoff = self.WATCH_BACKOFF_INITIAL
                    if chaos.fire(chaos.SITE_REST_WATCH_EVENT,
                                  kind=rt.kind) is not None:
                        break  # injected mid-stream drop → reconnect from rv
                # Clean close: back off too — a server that closes streams on
                # arrival (overflow, shutdown races) must not induce a hot
                # list/watch spin; delivered events above reset the backoff.
                self._watch_stop.wait(backoff)
                backoff = self._next_backoff(backoff)
            except (ConnectionError, OSError, ApiError,
                    json.JSONDecodeError) as exc:
                if self._watch_stop.is_set():
                    break
                _log.warning(
                    "watch stream died; reconnecting",
                    extra={"kv": {"kind": rt.kind, "rv": rv,
                                  "error": repr(exc),
                                  "backoff_s": round(backoff, 2)}})
                self._watch_stop.wait(backoff)
                backoff = self._next_backoff(backoff)
            finally:
                if conn is not None:
                    conn.close()

    def close(self) -> None:
        self._watch_stop.set()
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
