"""In-memory cluster: the API-server semantics the controllers depend on.

Implements the k8s behaviors the reference leans on implicitly (SURVEY §1 L0):

* optimistic concurrency — writes bump ``resourceVersion``; stale writes raise
  ``ConflictError`` (the reference scatters conflict-tolerant status updates,
  e.g. controllers/common/job.go:331-340 — our controllers must face the same
  failure mode to be honest);
* finalizers — delete stamps ``deletionTimestamp`` and the object lingers until
  its finalizer list drains (the preempt-protector protocol, SURVEY §3.3);
* ownerReference cascade GC — deleting an owner deletes its dependents (how job
  deletion cleans up pods/services in the reference);
* label selection and namespaces;
* watch events for controller wiring.

Thread-safe: one re-entrant lock around the store; watch callbacks fire outside
mutation where possible but may re-enter the API.
"""
from __future__ import annotations

import itertools
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple

from tpu_on_k8s.api.core import Event, ObjectMeta, ObjectReference, utcnow
from tpu_on_k8s.utils import serde


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    """resourceVersion mismatch — caller must re-read and retry."""


class ConflictRetriesExhausted(ConflictError):
    """A bounded read-modify-write loop saw nothing but 409s for its whole
    attempt budget — sustained contention (or an injected chaos schedule),
    not the ordinary losing-one-race case. Subclasses ``ConflictError`` so
    callers that treat any conflict as retryable-later keep working; callers
    that want to alert on livelock can catch this specifically."""


def run_conflict_retries(attempts: int, attempt: Callable[[], Any],
                         describe: str, metrics: Any = None) -> Any:
    """THE bounded conflict-retry loop — shared by every read-modify-write
    path (in-memory and REST ``update_with_retry``, REST finalizer
    ``patch_meta``) so the retry contract lives in one place. ``attempt``
    performs one full read-mutate-write; each retried ``ConflictError``
    feeds the ``conflict_retries`` counter on ``metrics`` (when wired);
    exhaustion raises the typed ``ConflictRetriesExhausted``."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    last: Optional[Exception] = None
    for _ in range(attempts):
        try:
            return attempt()
        except ConflictError as e:
            last = e
            if metrics is not None:
                metrics.inc("conflict_retries")
    raise ConflictRetriesExhausted(
        f"{describe} still conflicted after {attempts} attempts: "
        f"{last}") from last


class ExpiredError(ApiError):
    """Requested watch resourceVersion fell off the history window (the
    apiserver's 410 Gone) — the client must re-list and re-watch."""


@dataclass
class WatchEvent:
    type: str  # "ADDED" | "MODIFIED" | "DELETED"
    kind: str
    obj: Any
    old_obj: Any = None


Key = Tuple[str, str, str]  # (kind, namespace, name)


def match_labels(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class InMemoryCluster:
    """API-server stand-in. Objects are any dataclass with ``kind``/``metadata``;
    all reads return deep copies (mutating a returned object never mutates the
    store — exactly the informer-cache discipline the reference's controllers
    must respect)."""

    #: how many trailing watch events stay replayable for ?resourceVersion=N
    #: reconnects before the server answers 410 Gone.
    WATCH_HISTORY = 4096

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._store: Dict[Key, Any] = {}
        self._rv_counter = 0
        self._uid = itertools.count(1)
        self._watchers: List[Callable[[WatchEvent], None]] = []
        self._ordered_watchers: List[Callable[[WatchEvent], None]] = []
        self._history: Deque[Tuple[int, WatchEvent]] = deque(
            maxlen=self.WATCH_HISTORY)
        self._pod_logs: Dict[Tuple[str, str], List[str]] = {}

    # ---- watch ----------------------------------------------------------------
    def watch(self, callback: Callable[[WatchEvent], None],
              kinds: Optional[Iterable[str]] = None) -> None:
        """Register a live-event callback. ``kinds`` narrows delivery to the
        named kinds (the REST backend additionally narrows which informer
        streams it runs; here it is a dispatch filter)."""
        if kinds is not None:
            wanted = frozenset(kinds)
            original = callback

            def callback(event, _cb=original, _kinds=wanted):
                if event.kind in _kinds:
                    _cb(event)

        with self._lock:
            # registration races the mutation-side fanout (any writer
            # thread iterates this list): publish the append under the
            # same lock so a new watcher either sees an event or doesn't
            # — never a torn list
            self._watchers.append(callback)

    def subscribe_ordered(self, callback: Callable[[WatchEvent], None]) -> None:
        """Register a callback invoked INSIDE the mutation lock, in strict
        resourceVersion order (the apiserver's watch hub needs this: rv
        assignment and publication must be atomic or concurrent writers can
        publish out of order and a monotonic stream filter drops events).
        Callbacks must be fast and must not call back into the cluster."""
        with self._lock:
            self._ordered_watchers.append(callback)

    def _record(self, event: WatchEvent) -> None:
        """Publish under the mutation lock (caller holds ``self._lock``):
        history append + ordered fanout happen atomically with the rv
        assignment, so history and hub queues are rv-sorted."""
        self._history.append((event.obj.metadata.resource_version, event))
        for cb in list(self._ordered_watchers):
            cb(event)

    def _emit(self, event: WatchEvent) -> None:
        """Plain-callback fanout: snapshot the registry under the lock,
        call OUTSIDE it (callbacks may re-enter the API — the in-process
        controller wiring)."""
        with self._lock:
            cbs = list(self._watchers)
        for cb in cbs:
            cb(event)

    @property
    def current_rv(self) -> int:
        """The cluster-wide revision (what a conformant list's
        ``metadata.resourceVersion`` reports — etcd-revision semantics)."""
        with self._lock:
            return self._rv_counter

    def events_since(self, rv: int) -> List[WatchEvent]:
        """Replay buffered watch events with revision > rv, for
        ``?watch=true&resourceVersion=N``. Raises ExpiredError (→ 410 Gone)
        when rv is older than the history window."""
        with self._lock:
            if rv > self._rv_counter:
                # A future revision is unservable (etcd semantics) — happens
                # when the server restarted with fresh storage; the client
                # must re-list rather than wait for revisions that will
                # arrive with unrelated numbering.
                raise ExpiredError(
                    f"resourceVersion {rv} is ahead of the server "
                    f"({self._rv_counter})")
            if rv == self._rv_counter:
                return []
            if not self._history or self._history[0][0] > rv + 1:
                raise ExpiredError(
                    f"resourceVersion {rv} is too old "
                    f"(history starts at "
                    f"{self._history[0][0] if self._history else 'empty'})")
            return [e for r, e in self._history if r > rv]

    # ---- helpers --------------------------------------------------------------
    def _next_rv(self) -> int:
        with self._lock:
            self._rv_counter += 1
            return self._rv_counter

    @staticmethod
    def _key_of(obj: Any) -> Key:
        return (obj.kind, obj.metadata.namespace, obj.metadata.name)

    def record_event(self, obj: Any, etype: str, reason: str, message: str) -> None:
        """k8s Event recorder (reference record.EventRecorder): stores a real
        core/v1 Event object, named `{involved}.{seq}` like kubelet/clients do."""
        now = utcnow()
        ev = Event(
            metadata=ObjectMeta(
                name=f"{obj.metadata.name}.{next(self._uid):x}",
                namespace=obj.metadata.namespace or "default"),
            involved_object=ObjectReference(
                api_version=getattr(obj, "api_version", ""), kind=obj.kind,
                namespace=obj.metadata.namespace, name=obj.metadata.name,
                uid=obj.metadata.uid),
            type=etype, reason=reason, message=message,
            first_timestamp=now, last_timestamp=now)
        self.create(ev)

    @property
    def events(self) -> List[Tuple[str, str, str, str]]:
        """Stored Events as (namespace/name, type, reason, message) tuples in
        arrival order — the assertion surface tests use."""
        with self._lock:
            evs = [o for (k, _, _), o in self._store.items() if k == "Event"]
        evs.sort(key=lambda e: e.metadata.resource_version)
        return [(f"{e.involved_object.namespace}/{e.involved_object.name}",
                 e.type, e.reason, e.message) for e in evs]

    # ---- pod logs -------------------------------------------------------------
    def append_pod_log(self, namespace: str, name: str, line: str) -> None:
        """Kubelet-side log write (what a training process's stdout becomes)."""
        with self._lock:
            self._pod_logs.setdefault((namespace, name), []).append(line)

    def read_pod_log(self, namespace: str, name: str, *, tail: int = 0) -> List[str]:
        """pods/log subresource analog (the torchelastic metric observer reads
        one tail line this way — reference observation.go:40-106)."""
        with self._lock:
            lines = list(self._pod_logs.get((namespace, name), []))
        return lines[-tail:] if tail > 0 else lines

    # ---- CRUD -----------------------------------------------------------------
    def create(self, obj: Any) -> Any:
        with self._lock:
            key = self._key_of(obj)
            if key in self._store:
                raise AlreadyExistsError(f"{key} already exists")
            stored = serde.deep_copy(obj)
            meta = stored.metadata
            meta.uid = meta.uid or f"uid-{next(self._uid)}"
            meta.creation_timestamp = meta.creation_timestamp or utcnow()
            meta.resource_version = self._next_rv()
            meta.generation = max(meta.generation, 1)
            self._store[key] = stored
            out = serde.deep_copy(stored)
            event = WatchEvent("ADDED", obj.kind, out)
            self._record(event)
        self._emit(event)
        return out

    def get(self, cls: type, namespace: str, name: str) -> Any:
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        with self._lock:
            obj = self._store.get((kind, namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return serde.deep_copy(obj)

    def try_get(self, cls: type, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(cls, namespace, name)
        except NotFoundError:
            return None

    def list(
        self,
        cls: type,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        with self._lock:
            out = []
            for (k, ns, _), obj in self._store.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if label_selector and not match_labels(obj.metadata.labels, label_selector):
                    continue
                out.append(serde.deep_copy(obj))
            return out

    def update(self, obj: Any, *, subresource: str = "") -> Any:
        """Full-object update with optimistic concurrency. ``subresource="status"``
        mimics the status subresource: only status (and annotations/labels for
        protocol updates) are taken from the caller's object; spec is kept.
        Spec changes bump ``metadata.generation`` (k8s semantics the elastic
        generation protocol depends on, SURVEY §3.3)."""
        with self._lock:
            key = self._key_of(obj)
            current = self._store.get(key)
            if current is None:
                raise NotFoundError(f"{key} not found")
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.metadata.resource_version} "
                    f"!= {current.metadata.resource_version}"
                )
            old = serde.deep_copy(current)
            stored = serde.deep_copy(obj)
            if subresource == "status":
                stored.spec = current.spec
                stored.metadata.generation = current.metadata.generation
            else:
                old_spec = serde.to_dict(current.spec, drop_none=False) if hasattr(current, "spec") else None
                new_spec = serde.to_dict(stored.spec, drop_none=False) if hasattr(stored, "spec") else None
                if old_spec != new_spec:
                    stored.metadata.generation = current.metadata.generation + 1
                else:
                    stored.metadata.generation = current.metadata.generation
            # Immutable server-side fields.
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            stored.metadata.resource_version = self._next_rv()
            self._store[key] = stored
            out = serde.deep_copy(stored)
            event = WatchEvent("MODIFIED", obj.kind, out, old)
            self._record(event)
        self._emit(event)
        # A finalizer drain on a deleting object may complete the delete.
        if out.metadata.deletion_timestamp is not None and not out.metadata.finalizers:
            self._finalize_delete(self._key_of(out))
        return out

    def patch_meta(
        self,
        cls: type,
        namespace: str,
        name: str,
        *,
        labels: Optional[Dict[str, Optional[str]]] = None,
        annotations: Optional[Dict[str, Optional[str]]] = None,
        add_finalizers: Iterable[str] = (),
        remove_finalizers: Iterable[str] = (),
    ) -> Any:
        """Strategic-merge-style metadata patch (reference pkg/utils/patch). A
        value of None deletes the key. Patches never conflict — they re-read
        inside the lock (mirroring server-side patch semantics)."""
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        with self._lock:
            current = self._store.get((kind, namespace, name))
            if current is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            old = serde.deep_copy(current)
            for src, dst in ((labels, current.metadata.labels),
                             (annotations, current.metadata.annotations)):
                if src:
                    for k, v in src.items():
                        if v is None:
                            dst.pop(k, None)
                        else:
                            dst[k] = v
            for f in add_finalizers:
                if f not in current.metadata.finalizers:
                    current.metadata.finalizers.append(f)
            for f in remove_finalizers:
                if f in current.metadata.finalizers:
                    current.metadata.finalizers.remove(f)
            current.metadata.resource_version = self._next_rv()
            out = serde.deep_copy(current)
            event = WatchEvent("MODIFIED", kind, out, old)
            self._record(event)
        self._emit(event)
        if out.metadata.deletion_timestamp is not None and not out.metadata.finalizers:
            self._finalize_delete((kind, namespace, name))
        return out

    def merge_patch(self, cls: type, namespace: str, name: str,
                    patch: Dict[str, Any]) -> Any:
        """RFC 7386 JSON merge-patch — what a conformant apiserver executes
        for ``Content-Type: application/merge-patch+json`` (the verb
        RestCluster emits; reference builds the analogous merge payloads in
        pkg/utils/patch/patch.go:66-96). ``metadata.resourceVersion`` in the
        patch is an optimistic-concurrency precondition (409 on mismatch);
        null values delete keys; lists are replaced wholesale."""
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]

        def merge(target: Any, delta: Any) -> Any:
            if not isinstance(delta, dict) or not isinstance(target, dict):
                return delta
            out = dict(target)
            for k, v in delta.items():
                if v is None:
                    out.pop(k, None)
                elif isinstance(v, dict) and isinstance(out.get(k), dict):
                    out[k] = merge(out[k], v)
                else:
                    out[k] = v
            return out

        with self._lock:
            current = self._store.get((kind, namespace, name))
            if current is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            pre_rv = (patch.get("metadata") or {}).get("resourceVersion")
            if pre_rv is not None and int(pre_rv) != current.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {namespace}/{name}: patch precondition "
                    f"resourceVersion {pre_rv} != "
                    f"{current.metadata.resource_version}")
            old = serde.deep_copy(current)
            merged = merge(serde.to_dict(current, drop_none=False, wire=True),
                           patch)
            stored = serde.from_dict(cls, merged)
            # Server-side immutable fields win over whatever the patch said.
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            stored.metadata.namespace = current.metadata.namespace
            stored.metadata.name = current.metadata.name
            if hasattr(current, "spec"):
                old_spec = serde.to_dict(current.spec, drop_none=False)
                new_spec = serde.to_dict(stored.spec, drop_none=False)
                stored.metadata.generation = (
                    current.metadata.generation + (old_spec != new_spec))
            stored.metadata.resource_version = self._next_rv()
            self._store[(kind, namespace, name)] = stored
            out = serde.deep_copy(stored)
            event = WatchEvent("MODIFIED", kind, out, old)
            self._record(event)
        self._emit(event)
        if out.metadata.deletion_timestamp is not None and not out.metadata.finalizers:
            self._finalize_delete((kind, namespace, name))
        return out

    def delete(self, cls: type, namespace: str, name: str) -> None:
        """Graceful delete: with finalizers present, only stamps
        deletionTimestamp (the object becomes a "victim" in the preemption
        protocol); otherwise removes and cascades to ownerRef dependents."""
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        key = (kind, namespace, name)
        with self._lock:
            current = self._store.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            if current.metadata.finalizers:
                if current.metadata.deletion_timestamp is None:
                    current.metadata.deletion_timestamp = utcnow()
                    current.metadata.resource_version = self._next_rv()
                    out = serde.deep_copy(current)
                    event = WatchEvent("MODIFIED", kind, out)
                    self._record(event)
                else:
                    return  # already deleting
            else:
                out = None
        if out is not None:
            self._emit(event)
            return
        self._finalize_delete(key)

    def _finalize_delete(self, key: Key) -> None:
        with self._lock:
            obj = self._store.pop(key, None)
            if obj is None:
                return
            if key[0] == "Pod":
                # A recreated pod must NOT inherit its dead predecessor's log
                # stream (real pods/log is per-container-instance).
                self._pod_logs.pop((key[1], key[2]), None)
            # The deletion itself is a revision (etcd semantics): the DELETED
            # event carries a fresh rv so watch replay stays dense/ordered.
            obj.metadata.resource_version = self._next_rv()
            uid = obj.metadata.uid
            dependents = [
                (k, o) for k, o in self._store.items()
                if any(ref.uid == uid for ref in o.metadata.owner_references)
            ]
            event = WatchEvent("DELETED", key[0], serde.deep_copy(obj))
            self._record(event)
        self._emit(event)
        for (dkind, dns, dname), dobj in dependents:
            # Cascade GC (background propagation): finalizers still honored.
            try:
                self.delete(type(dobj), dns, dname)
            except NotFoundError:
                pass

    # ---- conveniences ---------------------------------------------------------
    def update_with_retry(self, cls: type, namespace: str, name: str,
                          mutate: Callable[[Any], None], *, subresource: str = "",
                          attempts: int = 5) -> Any:
        """Read-mutate-write with conflict retry — the centralized analog of the
        reference's scattered RetryOnConflict blocks (SURVEY §7 hard parts).
        Bounded: sustained 409s past ``attempts`` raise the typed
        ``ConflictRetriesExhausted`` (same contract as ``RestCluster``)."""
        def attempt() -> Any:
            obj = self.get(cls, namespace, name)
            mutate(obj)
            return self.update(obj, subresource=subresource)

        return run_conflict_retries(attempts, attempt,
                                    f"update of {namespace}/{name}",
                                    getattr(self, "metrics", None))
