"""In-memory cluster: the API-server semantics the controllers depend on.

Implements the k8s behaviors the reference leans on implicitly (SURVEY §1 L0):

* optimistic concurrency — writes bump ``resourceVersion``; stale writes raise
  ``ConflictError`` (the reference scatters conflict-tolerant status updates,
  e.g. controllers/common/job.go:331-340 — our controllers must face the same
  failure mode to be honest);
* finalizers — delete stamps ``deletionTimestamp`` and the object lingers until
  its finalizer list drains (the preempt-protector protocol, SURVEY §3.3);
* ownerReference cascade GC — deleting an owner deletes its dependents (how job
  deletion cleans up pods/services in the reference);
* label selection and namespaces;
* watch events for controller wiring.

Thread-safe: one re-entrant lock around the store; watch callbacks fire outside
mutation where possible but may re-enter the API.
"""
from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from tpu_on_k8s.api.core import ObjectMeta, utcnow
from tpu_on_k8s.utils import serde


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    """resourceVersion mismatch — caller must re-read and retry."""


@dataclass
class WatchEvent:
    type: str  # "ADDED" | "MODIFIED" | "DELETED"
    kind: str
    obj: Any
    old_obj: Any = None


Key = Tuple[str, str, str]  # (kind, namespace, name)


def match_labels(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class InMemoryCluster:
    """API-server stand-in. Objects are any dataclass with ``kind``/``metadata``;
    all reads return deep copies (mutating a returned object never mutates the
    store — exactly the informer-cache discipline the reference's controllers
    must respect)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._store: Dict[Key, Any] = {}
        self._rv = itertools.count(1)
        self._uid = itertools.count(1)
        self._watchers: List[Callable[[WatchEvent], None]] = []
        self.events: List[Tuple[str, str, str, str]] = []  # (obj name, type, reason, msg)
        self._pod_logs: Dict[Tuple[str, str], List[str]] = {}

    # ---- watch ----------------------------------------------------------------
    def watch(self, callback: Callable[[WatchEvent], None]) -> None:
        self._watchers.append(callback)

    def _emit(self, event: WatchEvent) -> None:
        for cb in list(self._watchers):
            cb(event)

    # ---- helpers --------------------------------------------------------------
    @staticmethod
    def _key_of(obj: Any) -> Key:
        return (obj.kind, obj.metadata.namespace, obj.metadata.name)

    def record_event(self, obj: Any, etype: str, reason: str, message: str) -> None:
        """k8s Event analog (reference record.EventRecorder)."""
        with self._lock:
            self.events.append((f"{obj.metadata.namespace}/{obj.metadata.name}", etype, reason, message))

    # ---- pod logs -------------------------------------------------------------
    def append_pod_log(self, namespace: str, name: str, line: str) -> None:
        """Kubelet-side log write (what a training process's stdout becomes)."""
        with self._lock:
            self._pod_logs.setdefault((namespace, name), []).append(line)

    def read_pod_log(self, namespace: str, name: str, *, tail: int = 0) -> List[str]:
        """pods/log subresource analog (the torchelastic metric observer reads
        one tail line this way — reference observation.go:40-106)."""
        with self._lock:
            lines = list(self._pod_logs.get((namespace, name), []))
        return lines[-tail:] if tail > 0 else lines

    # ---- CRUD -----------------------------------------------------------------
    def create(self, obj: Any) -> Any:
        with self._lock:
            key = self._key_of(obj)
            if key in self._store:
                raise AlreadyExistsError(f"{key} already exists")
            stored = serde.deep_copy(obj)
            meta = stored.metadata
            meta.uid = meta.uid or f"uid-{next(self._uid)}"
            meta.creation_timestamp = meta.creation_timestamp or utcnow()
            meta.resource_version = next(self._rv)
            meta.generation = max(meta.generation, 1)
            self._store[key] = stored
            out = serde.deep_copy(stored)
        self._emit(WatchEvent("ADDED", obj.kind, out))
        return out

    def get(self, cls: type, namespace: str, name: str) -> Any:
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        with self._lock:
            obj = self._store.get((kind, namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return serde.deep_copy(obj)

    def try_get(self, cls: type, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(cls, namespace, name)
        except NotFoundError:
            return None

    def list(
        self,
        cls: type,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        with self._lock:
            out = []
            for (k, ns, _), obj in self._store.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if label_selector and not match_labels(obj.metadata.labels, label_selector):
                    continue
                out.append(serde.deep_copy(obj))
            return out

    def update(self, obj: Any, *, subresource: str = "") -> Any:
        """Full-object update with optimistic concurrency. ``subresource="status"``
        mimics the status subresource: only status (and annotations/labels for
        protocol updates) are taken from the caller's object; spec is kept.
        Spec changes bump ``metadata.generation`` (k8s semantics the elastic
        generation protocol depends on, SURVEY §3.3)."""
        with self._lock:
            key = self._key_of(obj)
            current = self._store.get(key)
            if current is None:
                raise NotFoundError(f"{key} not found")
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.metadata.resource_version} "
                    f"!= {current.metadata.resource_version}"
                )
            old = serde.deep_copy(current)
            stored = serde.deep_copy(obj)
            if subresource == "status":
                stored.spec = current.spec
                stored.metadata.generation = current.metadata.generation
            else:
                old_spec = serde.to_dict(current.spec, drop_none=False) if hasattr(current, "spec") else None
                new_spec = serde.to_dict(stored.spec, drop_none=False) if hasattr(stored, "spec") else None
                if old_spec != new_spec:
                    stored.metadata.generation = current.metadata.generation + 1
                else:
                    stored.metadata.generation = current.metadata.generation
            # Immutable server-side fields.
            stored.metadata.uid = current.metadata.uid
            stored.metadata.creation_timestamp = current.metadata.creation_timestamp
            stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            stored.metadata.resource_version = next(self._rv)
            self._store[key] = stored
            out = serde.deep_copy(stored)
        self._emit(WatchEvent("MODIFIED", obj.kind, out, old))
        # A finalizer drain on a deleting object may complete the delete.
        if out.metadata.deletion_timestamp is not None and not out.metadata.finalizers:
            self._finalize_delete(self._key_of(out))
        return out

    def patch_meta(
        self,
        cls: type,
        namespace: str,
        name: str,
        *,
        labels: Optional[Dict[str, Optional[str]]] = None,
        annotations: Optional[Dict[str, Optional[str]]] = None,
        add_finalizers: Iterable[str] = (),
        remove_finalizers: Iterable[str] = (),
    ) -> Any:
        """Strategic-merge-style metadata patch (reference pkg/utils/patch). A
        value of None deletes the key. Patches never conflict — they re-read
        inside the lock (mirroring server-side patch semantics)."""
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        with self._lock:
            current = self._store.get((kind, namespace, name))
            if current is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            old = serde.deep_copy(current)
            for src, dst in ((labels, current.metadata.labels),
                             (annotations, current.metadata.annotations)):
                if src:
                    for k, v in src.items():
                        if v is None:
                            dst.pop(k, None)
                        else:
                            dst[k] = v
            for f in add_finalizers:
                if f not in current.metadata.finalizers:
                    current.metadata.finalizers.append(f)
            for f in remove_finalizers:
                if f in current.metadata.finalizers:
                    current.metadata.finalizers.remove(f)
            current.metadata.resource_version = next(self._rv)
            out = serde.deep_copy(current)
        self._emit(WatchEvent("MODIFIED", kind, out, old))
        if out.metadata.deletion_timestamp is not None and not out.metadata.finalizers:
            self._finalize_delete((kind, namespace, name))
        return out

    def delete(self, cls: type, namespace: str, name: str) -> None:
        """Graceful delete: with finalizers present, only stamps
        deletionTimestamp (the object becomes a "victim" in the preemption
        protocol); otherwise removes and cascades to ownerRef dependents."""
        kind = cls.__dataclass_fields__["kind"].default  # type: ignore[attr-defined]
        key = (kind, namespace, name)
        with self._lock:
            current = self._store.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            if current.metadata.finalizers:
                if current.metadata.deletion_timestamp is None:
                    current.metadata.deletion_timestamp = utcnow()
                    current.metadata.resource_version = next(self._rv)
                    out = serde.deep_copy(current)
                else:
                    return  # already deleting
            else:
                out = None
        if out is not None:
            self._emit(WatchEvent("MODIFIED", kind, out))
            return
        self._finalize_delete(key)

    def _finalize_delete(self, key: Key) -> None:
        with self._lock:
            obj = self._store.pop(key, None)
            if obj is None:
                return
            if key[0] == "Pod":
                # A recreated pod must NOT inherit its dead predecessor's log
                # stream (real pods/log is per-container-instance).
                self._pod_logs.pop((key[1], key[2]), None)
            uid = obj.metadata.uid
            dependents = [
                (k, o) for k, o in self._store.items()
                if any(ref.uid == uid for ref in o.metadata.owner_references)
            ]
        self._emit(WatchEvent("DELETED", key[0], serde.deep_copy(obj)))
        for (dkind, dns, dname), dobj in dependents:
            # Cascade GC (background propagation): finalizers still honored.
            try:
                self.delete(type(dobj), dns, dname)
            except NotFoundError:
                pass

    # ---- conveniences ---------------------------------------------------------
    def update_with_retry(self, cls: type, namespace: str, name: str,
                          mutate: Callable[[Any], None], *, subresource: str = "",
                          attempts: int = 5) -> Any:
        """Read-mutate-write with conflict retry — the centralized analog of the
        reference's scattered RetryOnConflict blocks (SURVEY §7 hard parts)."""
        last: Optional[Exception] = None
        for _ in range(attempts):
            obj = self.get(cls, namespace, name)
            mutate(obj)
            try:
                return self.update(obj, subresource=subresource)
            except ConflictError as e:
                last = e
        raise last  # type: ignore[misc]
