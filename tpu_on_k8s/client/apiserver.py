"""HTTP API server: Kubernetes REST semantics over the in-memory registry.

The envtest analog the reference's Makefile models (Makefile:106-109 spins a
real etcd+kube-apiserver for `go test`): a threaded HTTP server exposing the
API-machinery surface the controllers depend on —

* group/version/namespace REST routing (`/api/v1/...`, `/apis/{g}/{v}/...`)
  with real scoping (PersistentVolume / PriorityClass are cluster-scoped) and
  typed Status errors (NotFound / AlreadyExists / Conflict / Expired);
* camelCase wire JSON (``serde.to_dict(wire=True)``), snake_case storage;
* optimistic concurrency via resourceVersion on PUT (409 Conflict);
* the status subresource (`PUT .../{name}/status`);
* RFC 7386 JSON merge-patch (`Content-Type: application/merge-patch+json`)
  with resourceVersion preconditions — the same payloads the reference builds
  via pkg/utils/patch/patch.go:66-96, but in the patch dialect a conformant
  apiserver accepts for CRDs (strategic merge is built-ins-only in real k8s);
* graceful delete: finalizers pin the object with deletionTimestamp, drain
  completes the delete, ownerReference cascade GC follows;
* list responses carry ``metadata.resourceVersion`` (the global revision) so
  clients can list-then-watch without an event gap;
* streaming watch (`?watch=true`, chunked JSON lines, k8s wire format
  `{"type": ..., "object": ...}`) supporting ``resourceVersion=N`` resume
  from a bounded history window, ``410 Expired`` ERROR events when the
  window is exceeded (client must re-list), and optional BOOKMARK frames
  (``allowWatchBookmarks=true``) carrying the current revision;
* core/v1 Event objects through the ordinary CRUD routes;
* pods/log subresource (GET with `tailLines`).

Deliberate divergences from a conformant kube-apiserver (each is a test seam
or a scope cut, not a semantic the controllers depend on):

| Divergence | Why |
|---|---|
| `POST .../pods/{name}/log` injects a log line | kubelet stand-in: tests feed the stream the autoscaler's observer reads |
| label selectors support `k=v` equality only | the only form the controllers emit |
| no apiVersion conversion/validation webhooks | single-version API surface |
| client-cert authn is verify-only | TLS + Bearer tokens (the GKE ServiceAccount path) and optional mTLS via ``client_ca_path`` (CERT_REQUIRED against a CA, exercised by test_tls_over_rest.py); no username extraction from the cert subject — there is no RBAC layer to feed it to |

Storage delegates to `InMemoryCluster` — the same finalizer/cascade/conflict
logic the controllers were developed against — so this file is purely the
wire protocol. `RestCluster` (client/rest.py) is the typed client speaking
this protocol; reference analog: client/clientset/versioned/clientset.go.
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from tpu_on_k8s import chaos
from tpu_on_k8s.client import resources
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    ConflictError,
    ExpiredError,
    InMemoryCluster,
    NotFoundError,
    WatchEvent,
)
from tpu_on_k8s.utils import serde
from tpu_on_k8s.utils.logging import get_logger
from urllib.parse import parse_qs, urlparse

_log = get_logger("apiserver")


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps(_status_dict(code, reason, message)).encode()


def _status_dict(code: int, reason: str, message: str) -> Dict[str, Any]:
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code}


def encode_obj(obj: Any) -> Dict[str, Any]:
    return serde.to_dict(obj, drop_none=False, wire=True)


def decode_obj(rt: resources.ResourceType, data: Dict[str, Any]) -> Any:
    return serde.from_dict(rt.cls, data)


def parse_label_selector(raw: str) -> Optional[Dict[str, str]]:
    """`a=b,c=d` — the equality subset the controllers use."""
    if not raw:
        return None
    out: Dict[str, str] = {}
    for part in raw.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k] = v
    return out


class _Sub:
    """One watch subscriber: a bounded queue plus an overflow latch. A stalled
    consumer overflows, the stream closes, and the client re-lists — the
    honest semantics for an envtest analog (a real apiserver drops laggards
    the same way)."""

    MAXSIZE = 1024

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self.q: "queue.Queue" = queue.Queue(maxsize=self.MAXSIZE)
        self.overflowed = threading.Event()


class _WatchHub:
    """Fans cluster watch events out to per-connection bounded queues."""

    _CLOSE = object()

    def __init__(self, cluster: InMemoryCluster) -> None:
        self._lock = threading.Lock()
        self._subs: List[_Sub] = []
        # Ordered subscription: fanout happens atomically with rv assignment,
        # so per-stream queues are rv-sorted and the monotonic stream filter
        # never drops a reordered event.
        cluster.subscribe_ordered(self._on_event)

    def _on_event(self, event: WatchEvent) -> None:
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            if sub.kind != event.kind:
                continue
            try:
                sub.q.put_nowait(event)
            except queue.Full:
                sub.overflowed.set()
                self.unsubscribe(sub)

    def subscribe(self, kind: str) -> _Sub:
        sub = _Sub(kind)
        with self._lock:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: _Sub) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def close(self) -> None:
        with self._lock:
            subs = list(self._subs)
            self._subs = []
        for sub in subs:
            try:
                sub.q.put_nowait(self._CLOSE)
            except queue.Full:
                sub.overflowed.set()


class _Route:
    """Parsed request path."""

    def __init__(self, rt: resources.ResourceType, namespace: Optional[str],
                 name: Optional[str], subresource: Optional[str]):
        self.rt = rt
        self.namespace = namespace
        self.name = name
        self.subresource = subresource

    @property
    def store_namespace(self) -> str:
        """Namespace key for storage: cluster-scoped kinds live under ""."""
        if not self.rt.namespaced:
            return ""
        return self.namespace if self.namespace is not None else ""


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "tpu-on-k8s-apiserver"

    # set by ApiServer via type(); silence the type checker
    cluster: InMemoryCluster
    hub: _WatchHub
    stopping: threading.Event
    require_token: Optional[str] = None

    def log_message(self, fmt, *args):  # route through the framework logger
        _log.debug("%s %s", self.address_string(), fmt % args)

    def _authorized(self) -> bool:
        """Bearer-token check (what a real apiserver's authn layer does for
        ServiceAccount tokens). Enforced only when the server was started
        with a required token — the TLS tests pin the client's auth path."""
        if self.require_token is None:
            return True
        header = self.headers.get("Authorization", "")
        if header == f"Bearer {self.require_token}":
            return True
        self._send_json(401, _status_body(401, "Unauthorized",
                                          "bearer token missing or invalid"))
        return False

    def _chaos_fault(self) -> bool:
        """Server-side fault injection (``apiserver.request``): answer a
        typed failure or kill the connection before the verb runs. Returns
        True when a fault consumed the request."""
        fault = chaos.fire(chaos.SITE_APISERVER_REQUEST,
                           method=self.command, path=self.path)
        if fault is None:
            return False
        from tpu_on_k8s.chaos import faults as _faults
        if isinstance(fault, _faults.HttpError):
            self._send_json(fault.code, _status_body(
                fault.code, "InternalError", "chaos injected server error"))
            return True
        if isinstance(fault, _faults.Conflict):
            self._send_json(409, _status_body(
                409, "Conflict", "chaos injected write conflict"))
            return True
        # TimeoutFault / ConnectionResetFault / WatchDrop: the request never
        # gets an answer — close the socket so the client sees a reset (the
        # observable shape of both a timeout-then-close LB and a crashed
        # apiserver replica)
        self.close_connection = True
        try:
            self.connection.close()
        except OSError:
            pass
        return True

    # ------------------------------------------------------------------ routing
    def _parse(self) -> Tuple[Optional[_Route], Dict[str, List[str]]]:
        parsed = urlparse(self.path)
        qs = parse_qs(parsed.query)
        parts = [p for p in parsed.path.split("/") if p]
        # /api/v1/... vs /apis/{group}/{version}/...
        if len(parts) >= 2 and parts[0] == "api" and parts[1] == "v1":
            group, rest = "", parts[2:]
        elif len(parts) >= 3 and parts[0] == "apis":
            group, rest = parts[1], parts[3:]
        else:
            return None, qs
        namespace: Optional[str] = None
        if len(rest) >= 2 and rest[0] == "namespaces":
            namespace, rest = rest[1], rest[2:]
        if not rest:
            return None, qs
        plural, rest = rest[0], rest[1:]
        rt = resources.by_route(group, plural)
        if rt is None:
            return None, qs
        name = rest[0] if rest else None
        sub = rest[1] if len(rest) > 1 else None
        return _Route(rt, namespace, name, sub), qs

    # ---------------------------------------------------------------- responses
    def _send_json(self, code: int, payload: Any) -> None:
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_status(self, exc: Exception) -> None:
        if isinstance(exc, NotFoundError):
            self._send_json(404, _status_body(404, "NotFound", str(exc)))
        elif isinstance(exc, AlreadyExistsError):
            self._send_json(409, _status_body(409, "AlreadyExists", str(exc)))
        elif isinstance(exc, ConflictError):
            self._send_json(409, _status_body(409, "Conflict", str(exc)))
        elif isinstance(exc, ExpiredError):
            self._send_json(410, _status_body(410, "Expired", str(exc)))
        else:
            _log.exception("apiserver internal error")
            self._send_json(500, _status_body(500, "InternalError", str(exc)))

    def _read_body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw or b"{}")

    # ------------------------------------------------------------------- verbs
    def do_GET(self) -> None:
        if not self._authorized():
            return
        if self._chaos_fault():
            return
        route, qs = self._parse()
        if route is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            if route.name is None:
                if qs.get("watch", ["false"])[0] == "true":
                    self._stream_watch(route, qs)
                    return
                selector = parse_label_selector(
                    qs.get("labelSelector", [""])[0])
                # Revision first, list second: an event landing in between is
                # replayed by a watch from this revision — duplicates are safe
                # for level-triggered consumers; gaps are not.
                rv = self.cluster.current_rv
                ns = (route.store_namespace if (route.namespace is not None
                                                or not route.rt.namespaced)
                      else None)
                items = self.cluster.list(route.rt.cls, ns, selector)
                self._send_json(200, {
                    "kind": f"{route.rt.kind}List",
                    "apiVersion": (f"{route.rt.group}/{route.rt.version}"
                                   if route.rt.group else route.rt.version),
                    "metadata": {"resourceVersion": str(rv)},
                    "items": [encode_obj(o) for o in items]})
                return
            if route.subresource == "log":
                tail = int(qs.get("tailLines", ["0"])[0])
                lines = self.cluster.read_pod_log(route.store_namespace,
                                                  route.name, tail=tail)
                body = ("\n".join(lines)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            obj = self.cluster.get(route.rt.cls, route.store_namespace,
                                   route.name)
            self._send_json(200, encode_obj(obj))
        # analyze: allow[silent-loss] exc becomes a typed HTTP Status response (_send_error_status)
        except Exception as exc:  # noqa: BLE001 — mapped to Status codes
            self._send_error_status(exc)

    def do_POST(self) -> None:
        if not self._authorized():
            return
        if self._chaos_fault():
            return
        route, _ = self._parse()
        if route is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            body = self._read_body()
            if route.subresource == "log":
                # kubelet-side log injection (divergence table: test seam)
                self.cluster.append_pod_log(route.store_namespace, route.name,
                                            body.get("line", ""))
                self._send_json(200, {"status": "ok"})
                return
            obj = decode_obj(route.rt, body)
            if route.rt.namespaced:
                obj.metadata.namespace = (route.namespace
                                          or obj.metadata.namespace)
            else:
                obj.metadata.namespace = ""
            created = self.cluster.create(obj)
            self._send_json(201, encode_obj(created))
        # analyze: allow[silent-loss] exc becomes a typed HTTP Status response (_send_error_status)
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    def do_PUT(self) -> None:
        if not self._authorized():
            return
        if self._chaos_fault():
            return
        route, _ = self._parse()
        if route is None or route.name is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            obj = decode_obj(route.rt, self._read_body())
            if not route.rt.namespaced:
                obj.metadata.namespace = ""
            sub = "status" if route.subresource == "status" else ""
            updated = self.cluster.update(obj, subresource=sub)
            self._send_json(200, encode_obj(updated))
        # analyze: allow[silent-loss] exc becomes a typed HTTP Status response (_send_error_status)
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    def do_PATCH(self) -> None:
        if not self._authorized():
            return
        if self._chaos_fault():
            return
        route, _ = self._parse()
        if route is None or route.name is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype not in ("application/merge-patch+json",
                        "application/json", ""):
            self._send_json(415, _status_body(
                415, "UnsupportedMediaType",
                f"patch content type {ctype!r} not supported "
                f"(use application/merge-patch+json)"))
            return
        try:
            patched = self.cluster.merge_patch(
                route.rt.cls, route.store_namespace, route.name,
                self._read_body())
            self._send_json(200, encode_obj(patched))
        # analyze: allow[silent-loss] exc becomes a typed HTTP Status response (_send_error_status)
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    def do_DELETE(self) -> None:
        if not self._authorized():
            return
        if self._chaos_fault():
            return
        route, _ = self._parse()
        if route is None or route.name is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            self.cluster.delete(route.rt.cls, route.store_namespace,
                                route.name)
            self._send_json(200, {"kind": "Status", "status": "Success"})
        # analyze: allow[silent-loss] exc becomes a typed HTTP Status response (_send_error_status)
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    # -------------------------------------------------------------------- watch
    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _watch_frame(self, etype: str, payload: Dict[str, Any]) -> bytes:
        return json.dumps({"type": etype, "object": payload}).encode() + b"\n"

    def _bookmark(self, route: _Route, rv: int) -> bytes:
        api_version = (f"{route.rt.group}/{route.rt.version}"
                       if route.rt.group else route.rt.version)
        return self._watch_frame("BOOKMARK", {
            "kind": route.rt.kind, "apiVersion": api_version,
            "metadata": {"resourceVersion": str(rv)}})

    def _stream_watch(self, route: _Route, qs: Dict[str, List[str]]) -> None:
        since: Optional[int] = None
        raw_rv = qs.get("resourceVersion", [""])[0]
        if raw_rv:
            since = int(raw_rv)
        bookmarks = qs.get("allowWatchBookmarks", ["false"])[0] == "true"

        sub = self.hub.subscribe(route.rt.kind)
        try:
            replay: List[WatchEvent] = []
            if since is not None:
                try:
                    replay = [e for e in self.cluster.events_since(since)
                              if e.kind == route.rt.kind]
                except ExpiredError as exc:
                    self._send_json(410, _status_body(410, "Expired", str(exc)))
                    return
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            last_rv = since if since is not None else self.cluster.current_rv
            if bookmarks:
                self._write_chunk(self._bookmark(route, last_rv))

            def deliver(event: WatchEvent) -> None:
                nonlocal last_rv
                rv = event.obj.metadata.resource_version
                if rv <= last_rv:
                    return  # replay/live overlap — already sent
                if (route.namespace is not None
                        and event.obj.metadata.namespace != route.namespace):
                    last_rv = rv
                    return
                self._write_chunk(self._watch_frame(event.type,
                                                    encode_obj(event.obj)))
                last_rv = rv

            for event in replay:
                deliver(event)
            idle = 0
            while not self.stopping.is_set():
                if sub.overflowed.is_set():
                    break  # close: client re-lists (bounded-queue semantics)
                try:
                    event = sub.q.get(timeout=0.5)
                    idle = 0
                except queue.Empty:
                    idle += 1
                    if bookmarks and idle % 10 == 0:
                        # Bookmark the last revision actually DELIVERED on
                        # this stream — advertising cluster.current_rv could
                        # skip events still queued here if the client resumes
                        # from the bookmark after a drop.
                        self._write_chunk(self._bookmark(route, last_rv))
                    continue
                if event is _WatchHub._CLOSE:
                    break
                deliver(event)
                if chaos.fire(chaos.SITE_APISERVER_WATCH,
                              kind=route.rt.kind) is not None:
                    break  # injected server-side stream drop: the client
                           # must resume from its last delivered revision
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away
        finally:
            self.hub.unsubscribe(sub)
            try:
                self._write_chunk(b"")  # terminating chunk
            except OSError:
                pass


class ApiServer:
    """Lifecycle wrapper: `start()` serves on a background thread pool,
    `stop()` drains watch streams and shuts down."""

    def __init__(self, cluster: Optional[InMemoryCluster] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tls_cert_path: Optional[str] = None,
                 tls_key_path: Optional[str] = None,
                 require_token: Optional[str] = None,
                 client_ca_path: Optional[str] = None) -> None:
        """``tls_cert_path``/``tls_key_path`` serve HTTPS (what a real
        apiserver always does); ``require_token`` additionally enforces
        Bearer auth on every verb — together they exercise the client's
        ca_path/token_path path instead of leaving it dead in tests.
        ``client_ca_path`` demands a client certificate signed by that CA
        (mutual TLS — the kubeconfig client-certificate auth mode)."""
        self.cluster = cluster or InMemoryCluster()
        self.hub = _WatchHub(self.cluster)
        self._stopping = threading.Event()
        handler = type("BoundHandler", (_Handler,), {
            "cluster": self.cluster, "hub": self.hub,
            "stopping": self._stopping, "require_token": require_token})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.tls = bool(tls_cert_path)
        if self.tls:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert_path, tls_key_path)
            if client_ca_path:
                ctx.verify_mode = ssl.CERT_REQUIRED
                ctx.load_verify_locations(cafile=client_ca_path)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.host}:{self.port}"

    def start(self) -> "ApiServer":
        # analyze: allow[thread-roots] stdlib serve_forever only accepts sockets; the request threads it spawns are modeled by the http:_Handler root
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.05},
                                        daemon=True, name="apiserver")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        self.hub.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
