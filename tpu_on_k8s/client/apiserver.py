"""HTTP API server: Kubernetes REST semantics over the in-memory registry.

The envtest analog the reference's Makefile models (Makefile:106-109 spins a
real etcd+kube-apiserver for `go test`): a threaded HTTP server exposing the
API-machinery surface the controllers depend on —

* group/version/namespace REST routing (`/api/v1/...`, `/apis/{g}/{v}/...`)
  with typed Status errors (NotFound / AlreadyExists / Conflict);
* optimistic concurrency via resourceVersion on PUT (409 Conflict);
* the status subresource (`PUT .../{name}/status`);
* strategic metadata PATCH with finalizer add/remove (the reference's patch
  DSL, pkg/utils/patch/patch.go:66-96, incl. `$deleteFromPrimitiveList`);
* graceful delete: finalizers pin the object with deletionTimestamp, drain
  completes the delete, ownerReference cascade GC follows;
* streaming watch (`?watch=true`, chunked JSON lines, k8s wire format
  `{"type": ..., "object": ...}`) with an initial BOOKMARK so clients can
  block until the stream is live (no missed-event gap);
* pods/log subresource (GET with `tailLines`; POST is the kubelet-side
  injection seam tests use, the one non-k8s extension);
* core/v1 Events (POST + GET).

Storage delegates to `InMemoryCluster` — the same finalizer/cascade/conflict
logic the controllers were developed against — so this file is purely the
wire protocol. `RestCluster` (client/rest.py) is the typed client speaking
this protocol; reference analog: client/clientset/versioned/clientset.go.
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from tpu_on_k8s.client import resources
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    ConflictError,
    InMemoryCluster,
    NotFoundError,
    WatchEvent,
)
from tpu_on_k8s.utils import serde
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("apiserver")


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps({"kind": "Status", "apiVersion": "v1",
                       "status": "Failure", "reason": reason,
                       "message": message, "code": code}).encode()


def encode_obj(obj: Any) -> Dict[str, Any]:
    return serde.to_dict(obj, drop_none=False)


def decode_obj(rt: resources.ResourceType, data: Dict[str, Any]) -> Any:
    return serde.from_dict(rt.cls, data)


def parse_label_selector(raw: str) -> Optional[Dict[str, str]]:
    """`a=b,c=d` — the equality subset the controllers use."""
    if not raw:
        return None
    out: Dict[str, str] = {}
    for part in raw.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k] = v
    return out


class _WatchHub:
    """Fans cluster watch events out to per-connection queues."""

    _CLOSE = object()

    def __init__(self, cluster: InMemoryCluster) -> None:
        self._lock = threading.Lock()
        self._subs: List[Tuple[str, "queue.Queue"]] = []  # (kind, q)
        cluster.watch(self._on_event)

    def _on_event(self, event: WatchEvent) -> None:
        with self._lock:
            subs = list(self._subs)
        for kind, q in subs:
            if kind == event.kind:
                q.put(event)

    def subscribe(self, kind: str) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            self._subs.append((kind, q))
        return q

    def unsubscribe(self, q: "queue.Queue") -> None:
        with self._lock:
            self._subs = [(k, s) for k, s in self._subs if s is not q]

    def close(self) -> None:
        with self._lock:
            subs = list(self._subs)
            self._subs = []
        for _, q in subs:
            q.put(self._CLOSE)


class _Route:
    """Parsed request path."""

    def __init__(self, rt: resources.ResourceType, namespace: Optional[str],
                 name: Optional[str], subresource: Optional[str]):
        self.rt = rt
        self.namespace = namespace
        self.name = name
        self.subresource = subresource


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "tpu-on-k8s-apiserver"

    # set by ApiServer via type(); silence the type checker
    cluster: InMemoryCluster
    hub: _WatchHub
    stopping: threading.Event

    def log_message(self, fmt, *args):  # route through the framework logger
        _log.debug("%s %s", self.address_string(), fmt % args)

    # ------------------------------------------------------------------ routing
    def _parse(self) -> Tuple[Optional[_Route], Dict[str, List[str]]]:
        parsed = urlparse(self.path)
        qs = parse_qs(parsed.query)
        parts = [p for p in parsed.path.split("/") if p]
        # /api/v1/... vs /apis/{group}/{version}/...
        if len(parts) >= 2 and parts[0] == "api" and parts[1] == "v1":
            group, rest = "", parts[2:]
        elif len(parts) >= 3 and parts[0] == "apis":
            group, rest = parts[1], parts[3:]
        else:
            return None, qs
        namespace: Optional[str] = None
        if len(rest) >= 2 and rest[0] == "namespaces":
            namespace, rest = rest[1], rest[2:]
        if not rest:
            return None, qs
        plural, rest = rest[0], rest[1:]
        if group == "" and plural == "events":
            # core/v1 Events have no dataclass kind; handled specially
            return _Route(None, namespace, rest[0] if rest else None, None), qs  # type: ignore[arg-type]
        rt = resources.by_route(group, plural)
        if rt is None:
            return None, qs
        name = rest[0] if rest else None
        sub = rest[1] if len(rest) > 1 else None
        return _Route(rt, namespace, name, sub), qs

    # ---------------------------------------------------------------- responses
    def _send_json(self, code: int, payload: Any) -> None:
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_status(self, exc: Exception) -> None:
        if isinstance(exc, NotFoundError):
            self._send_json(404, _status_body(404, "NotFound", str(exc)))
        elif isinstance(exc, AlreadyExistsError):
            self._send_json(409, _status_body(409, "AlreadyExists", str(exc)))
        elif isinstance(exc, ConflictError):
            self._send_json(409, _status_body(409, "Conflict", str(exc)))
        else:
            _log.exception("apiserver internal error")
            self._send_json(500, _status_body(500, "InternalError", str(exc)))

    def _read_body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw or b"{}")

    # ------------------------------------------------------------------- verbs
    def do_GET(self) -> None:
        route, qs = self._parse()
        if route is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            if route.rt is None:  # events
                self._send_json(200, {"items": [list(e) for e in self.cluster.events]})
                return
            if route.name is None:
                if qs.get("watch", ["false"])[0] == "true":
                    self._stream_watch(route)
                    return
                selector = parse_label_selector(
                    qs.get("labelSelector", [""])[0])
                items = self.cluster.list(route.rt.cls, route.namespace,
                                          selector)
                self._send_json(200, {"kind": f"{route.rt.kind}List",
                                      "items": [encode_obj(o) for o in items]})
                return
            if route.subresource == "log":
                tail = int(qs.get("tailLines", ["0"])[0])
                lines = self.cluster.read_pod_log(route.namespace, route.name,
                                                  tail=tail)
                body = ("\n".join(lines)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            obj = self.cluster.get(route.rt.cls, route.namespace, route.name)
            self._send_json(200, encode_obj(obj))
        except Exception as exc:  # noqa: BLE001 — mapped to Status codes
            self._send_error_status(exc)

    def do_POST(self) -> None:
        route, _ = self._parse()
        if route is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            body = self._read_body()
            if route.rt is None:  # POST core/v1 events
                inv = body.get("involvedObject", {})
                self.cluster.events.append(
                    (f"{inv.get('namespace', route.namespace)}/{inv.get('name', '')}",
                     body.get("type", "Normal"), body.get("reason", ""),
                     body.get("message", "")))
                self._send_json(201, {"status": "ok"})
                return
            if route.subresource == "log":
                # kubelet-side log injection (test seam; not real k8s REST)
                self.cluster.append_pod_log(route.namespace, route.name,
                                            body.get("line", ""))
                self._send_json(200, {"status": "ok"})
                return
            obj = decode_obj(route.rt, body)
            obj.metadata.namespace = route.namespace or obj.metadata.namespace
            created = self.cluster.create(obj)
            self._send_json(201, encode_obj(created))
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    def do_PUT(self) -> None:
        route, _ = self._parse()
        if route is None or route.rt is None or route.name is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            obj = decode_obj(route.rt, self._read_body())
            sub = "status" if route.subresource == "status" else ""
            updated = self.cluster.update(obj, subresource=sub)
            self._send_json(200, encode_obj(updated))
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    def do_PATCH(self) -> None:
        route, _ = self._parse()
        if route is None or route.rt is None or route.name is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            body = self._read_body()
            meta = body.get("metadata", {})
            patched = self.cluster.patch_meta(
                route.rt.cls, route.namespace, route.name,
                labels=meta.get("labels"),
                annotations=meta.get("annotations"),
                add_finalizers=meta.get("$addFinalizers", ()),
                remove_finalizers=meta.get("$removeFinalizers", ()))
            self._send_json(200, encode_obj(patched))
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    def do_DELETE(self) -> None:
        route, _ = self._parse()
        if route is None or route.rt is None or route.name is None:
            self._send_json(404, _status_body(404, "NotFound", self.path))
            return
        try:
            self.cluster.delete(route.rt.cls, route.namespace, route.name)
            self._send_json(200, {"kind": "Status", "status": "Success"})
        except Exception as exc:  # noqa: BLE001
            self._send_error_status(exc)

    # -------------------------------------------------------------------- watch
    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _stream_watch(self, route: _Route) -> None:
        q = self.hub.subscribe(route.rt.kind)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            # Initial bookmark: the client blocks on this to guarantee the
            # subscription is live before it returns from watch() — no gap
            # between "watch registered" and "events delivered".
            self._write_chunk(json.dumps({"type": "BOOKMARK"}).encode() + b"\n")
            while not self.stopping.is_set():
                try:
                    event = q.get(timeout=0.5)
                except queue.Empty:
                    continue
                if event is _WatchHub._CLOSE:
                    break
                if (route.namespace is not None
                        and event.obj.metadata.namespace != route.namespace):
                    continue
                line = json.dumps({"type": event.type,
                                   "object": encode_obj(event.obj)}).encode()
                self._write_chunk(line + b"\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away
        finally:
            self.hub.unsubscribe(q)
            try:
                self._write_chunk(b"")  # terminating chunk
            except OSError:
                pass


class ApiServer:
    """Lifecycle wrapper: `start()` serves on a background thread pool,
    `stop()` drains watch streams and shuts down."""

    def __init__(self, cluster: Optional[InMemoryCluster] = None,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.cluster = cluster or InMemoryCluster()
        self.hub = _WatchHub(self.cluster)
        self._stopping = threading.Event()
        handler = type("BoundHandler", (_Handler,), {
            "cluster": self.cluster, "hub": self.hub,
            "stopping": self._stopping})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.05},
                                        daemon=True, name="apiserver")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        self.hub.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
