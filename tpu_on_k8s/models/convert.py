"""HuggingFace Llama checkpoint interop.

``from_hf_llama`` maps a ``transformers`` ``LlamaForCausalLM`` (or its
state dict) onto this framework's flagship transformer
(`tpu_on_k8s/models/transformer.py`): users bring real Llama-family
weights, and — just as importantly — the mapping gives the whole stack an
INDEPENDENT external oracle: logit parity against HF's torch
implementation exercises rope (both use the rotate-half convention with
``inv_freq = theta^(-2i/d)``), GQA head grouping, SwiGLU, RMSNorm
epsilon handling, and the tied/untied head in one comparison no
self-authored test can fake (`tests/test_hf_interop.py`).

Layout notes: torch ``nn.Linear`` stores ``weight [out, in]`` and
computes ``x @ weight.T``; our kernels are ``[in, out]`` — every
projection transposes. Scanned blocks stack per-layer leaves on axis 0.
The reference operator never touches checkpoints beyond mounting them
(SURVEY.md §2.6); interop is compute-plane surface.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp
import numpy as np

from tpu_on_k8s.models.transformer import Transformer, TransformerConfig


def config_from_hf_llama(hf_config) -> TransformerConfig:
    """A ``TransformerConfig`` matching a ``transformers.LlamaConfig``."""
    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads)
    if head_dim * hf_config.num_attention_heads != hf_config.hidden_size:
        raise ValueError(
            f"unsupported head_dim {head_dim}: this transformer derives "
            f"head_dim as hidden_size/num_heads")
    if getattr(hf_config, "attention_bias", False):
        raise ValueError("attention_bias=True is not supported")
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(hf_config.rope_theta),
        norm_eps=float(hf_config.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings",
                                    False)),
        remat=False,
    )


def params_from_hf_llama(state_dict, cfg: TransformerConfig,
                         dtype=jnp.float32) -> dict:
    """Our param pytree from an HF Llama ``state_dict`` (torch tensors or
    numpy arrays)."""
    def arr(name: str) -> np.ndarray:
        w = state_dict[name]
        if hasattr(w, "detach"):          # torch tensor
            w = w.detach().to("cpu").float().numpy()
        return np.asarray(w, np.float32)

    def stacked(fmt: str, transpose: bool = True) -> jnp.ndarray:
        ws = [arr(fmt.format(i)) for i in range(cfg.n_layers)]
        ws = [w.T if transpose else w for w in ws]
        return jnp.asarray(np.stack(ws), dtype)

    blocks = {
        "attn": {
            "wq": {"kernel": stacked(
                "model.layers.{}.self_attn.q_proj.weight")},
            "wk": {"kernel": stacked(
                "model.layers.{}.self_attn.k_proj.weight")},
            "wv": {"kernel": stacked(
                "model.layers.{}.self_attn.v_proj.weight")},
            "wo": {"kernel": stacked(
                "model.layers.{}.self_attn.o_proj.weight")},
        },
        "attn_norm": {"scale": stacked(
            "model.layers.{}.input_layernorm.weight", transpose=False)},
        "mlp": {
            "w_gate": {"kernel": stacked(
                "model.layers.{}.mlp.gate_proj.weight")},
            "w_up": {"kernel": stacked(
                "model.layers.{}.mlp.up_proj.weight")},
            "w_down": {"kernel": stacked(
                "model.layers.{}.mlp.down_proj.weight")},
        },
        "mlp_norm": {"scale": stacked(
            "model.layers.{}.post_attention_layernorm.weight",
            transpose=False)},
    }
    params: dict[str, Any] = {
        "embed": jnp.asarray(arr("model.embed_tokens.weight"), dtype),
        "blocks": blocks,
        "final_norm": {"scale": jnp.asarray(arr("model.norm.weight"),
                                            dtype)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(arr("lm_head.weight").T, dtype)
    return params


def from_hf_llama(hf_model, dtype=jnp.float32, compute_dtype=None
                  ) -> Tuple[TransformerConfig, dict]:
    """(config, params) from a loaded ``LlamaForCausalLM`` — ready for
    ``Transformer``, ``generate()``, the continuous-batching engine, or a
    fine-tuning ``Trainer``.

    ``dtype`` stores the converted params; ``compute_dtype`` (default:
    same as ``dtype``) sets the model's activation dtype — pass
    ``jnp.bfloat16`` for TPU serving, keep fp32 when comparing logits
    against the HF oracle bit-closely."""
    import dataclasses

    cfg = config_from_hf_llama(hf_model.config)
    cfg = dataclasses.replace(cfg, dtype=compute_dtype or dtype,
                              param_dtype=dtype)
    params = params_from_hf_llama(hf_model.state_dict(), cfg, dtype)
    Transformer(cfg)  # config constructs; bad fields fail loudly here
    return cfg, params
