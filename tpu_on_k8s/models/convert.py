"""HuggingFace Llama checkpoint interop.

``from_hf_llama`` maps a ``transformers`` ``LlamaForCausalLM`` (or its
state dict) onto this framework's flagship transformer
(`tpu_on_k8s/models/transformer.py`): users bring real Llama-family
weights, and — just as importantly — the mapping gives the whole stack an
INDEPENDENT external oracle: logit parity against HF's torch
implementation exercises rope (both use the rotate-half convention with
``inv_freq = theta^(-2i/d)``), GQA head grouping, SwiGLU, RMSNorm
epsilon handling, and the tied/untied head in one comparison no
self-authored test can fake (`tests/test_hf_interop.py`).

Layout notes: torch ``nn.Linear`` stores ``weight [out, in]`` and
computes ``x @ weight.T``; our kernels are ``[in, out]`` — every
projection transposes. Scanned blocks stack per-layer leaves on axis 0.
The reference operator never touches checkpoints beyond mounting them
(SURVEY.md §2.6); interop is compute-plane surface.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp
import numpy as np

from tpu_on_k8s.models.transformer import Transformer, TransformerConfig


def _to_np(state_dict, name: str) -> np.ndarray:
    """fp32 numpy view of a state-dict entry (torch tensor or array)."""
    w = state_dict[name]
    if hasattr(w, "detach"):          # torch tensor
        w = w.detach().to("cpu").float().numpy()
    return np.asarray(w, np.float32)


def config_from_hf_llama(hf_config) -> TransformerConfig:
    """A ``TransformerConfig`` matching a ``transformers.LlamaConfig``."""
    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads)
    if head_dim * hf_config.num_attention_heads != hf_config.hidden_size:
        raise ValueError(
            f"unsupported head_dim {head_dim}: this transformer derives "
            f"head_dim as hidden_size/num_heads")
    if getattr(hf_config, "attention_bias", False):
        raise ValueError("attention_bias=True is not supported")
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling and scaling.get("rope_type", scaling.get("type")) != "default":
        # Llama-3.1+ rescales rope frequencies; converting silently would
        # produce wrong logits far from the trained context behavior
        raise ValueError(f"rope_scaling {scaling!r} is not supported "
                         f"(plain rope only)")
    act = getattr(hf_config, "hidden_act", "silu")
    if act != "silu":
        raise ValueError(f"unsupported hidden_act {act!r}: the SwiGLU MLP "
                         f"assumes silu gating")
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(hf_config.rope_theta),
        norm_eps=float(hf_config.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings",
                                    False)),
        remat=False,
    )


def params_from_hf_llama(state_dict, cfg: TransformerConfig,
                         dtype=jnp.float32) -> dict:
    """Our param pytree from an HF Llama ``state_dict`` (torch tensors or
    numpy arrays)."""
    def arr(name: str) -> np.ndarray:
        return _to_np(state_dict, name)

    def stacked(fmt: str, transpose: bool = True) -> jnp.ndarray:
        ws = [arr(fmt.format(i)) for i in range(cfg.n_layers)]
        ws = [w.T if transpose else w for w in ws]
        return jnp.asarray(np.stack(ws), dtype)

    blocks = {
        "attn": {
            "wq": {"kernel": stacked(
                "model.layers.{}.self_attn.q_proj.weight")},
            "wk": {"kernel": stacked(
                "model.layers.{}.self_attn.k_proj.weight")},
            "wv": {"kernel": stacked(
                "model.layers.{}.self_attn.v_proj.weight")},
            "wo": {"kernel": stacked(
                "model.layers.{}.self_attn.o_proj.weight")},
        },
        "attn_norm": {"scale": stacked(
            "model.layers.{}.input_layernorm.weight", transpose=False)},
        "mlp": {
            "w_gate": {"kernel": stacked(
                "model.layers.{}.mlp.gate_proj.weight")},
            "w_up": {"kernel": stacked(
                "model.layers.{}.mlp.up_proj.weight")},
            "w_down": {"kernel": stacked(
                "model.layers.{}.mlp.down_proj.weight")},
        },
        "mlp_norm": {"scale": stacked(
            "model.layers.{}.post_attention_layernorm.weight",
            transpose=False)},
    }
    params: dict[str, Any] = {
        "embed": jnp.asarray(arr("model.embed_tokens.weight"), dtype),
        "blocks": blocks,
        "final_norm": {"scale": jnp.asarray(arr("model.norm.weight"),
                                            dtype)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(arr("lm_head.weight").T, dtype)
    return params


def config_from_hf_gpt2(hf_config) -> TransformerConfig:
    """A ``TransformerConfig`` matching a ``transformers.GPT2Config``
    (learned positions, LayerNorm, (tanh-)gelu, tied embeddings, biased
    projections)."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported activation {act!r}: this framework's "
                         f"gelu is the tanh approximation")
    if getattr(hf_config, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx changes the "
                         "attention math; not supported")
    if getattr(hf_config, "reorder_and_upcast_attn", False):
        raise ValueError("reorder_and_upcast_attn changes the attention "
                         "math; not supported")
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.n_embd,
        n_layers=hf_config.n_layer,
        n_heads=hf_config.n_head,
        n_kv_heads=hf_config.n_head,
        d_ff=hf_config.n_inner or 4 * hf_config.n_embd,
        max_seq_len=hf_config.n_positions,
        norm_eps=float(hf_config.layer_norm_epsilon),
        pos_emb="learned", norm="ln", activation="gelu",
        use_bias=True, tie_embeddings=True, remat=False,
    )


def params_from_hf_gpt2(state_dict, cfg: TransformerConfig,
                        dtype=jnp.float32) -> dict:
    """Our param pytree from an HF GPT-2 ``state_dict``. GPT-2's Conv1D
    stores weights ``[in, out]`` (already our kernel layout — no
    transpose, unlike Llama's Linear); the fused c_attn splits into
    wq/wk/wv along the output dim in HF's q,k,v order."""
    def arr(name: str) -> np.ndarray:
        return _to_np(state_dict, name)

    d = cfg.d_model

    def stacked(fmt: str) -> np.ndarray:
        return np.stack([arr(fmt.format(i)) for i in range(cfg.n_layers)])

    c_attn_w = stacked("transformer.h.{}.attn.c_attn.weight")  # [L, D, 3D]
    c_attn_b = stacked("transformer.h.{}.attn.c_attn.bias")    # [L, 3D]

    def j(x):
        return jnp.asarray(x, dtype)

    blocks = {
        "attn": {
            "wq": {"kernel": j(c_attn_w[:, :, :d]),
                   "bias": j(c_attn_b[:, :d])},
            "wk": {"kernel": j(c_attn_w[:, :, d:2 * d]),
                   "bias": j(c_attn_b[:, d:2 * d])},
            "wv": {"kernel": j(c_attn_w[:, :, 2 * d:]),
                   "bias": j(c_attn_b[:, 2 * d:])},
            "wo": {"kernel": j(stacked(
                       "transformer.h.{}.attn.c_proj.weight")),
                   "bias": j(stacked(
                       "transformer.h.{}.attn.c_proj.bias"))},
        },
        "attn_norm": {"scale": j(stacked("transformer.h.{}.ln_1.weight")),
                      "bias": j(stacked("transformer.h.{}.ln_1.bias"))},
        "mlp": {
            "w_up": {"kernel": j(stacked(
                         "transformer.h.{}.mlp.c_fc.weight")),
                     "bias": j(stacked("transformer.h.{}.mlp.c_fc.bias"))},
            "w_down": {"kernel": j(stacked(
                           "transformer.h.{}.mlp.c_proj.weight")),
                       "bias": j(stacked(
                           "transformer.h.{}.mlp.c_proj.bias"))},
        },
        "mlp_norm": {"scale": j(stacked("transformer.h.{}.ln_2.weight")),
                     "bias": j(stacked("transformer.h.{}.ln_2.bias"))},
    }
    return {
        "embed": j(arr("transformer.wte.weight")),
        "pos_embed": j(arr("transformer.wpe.weight")),
        "blocks": blocks,
        "final_norm": {"scale": j(arr("transformer.ln_f.weight")),
                       "bias": j(arr("transformer.ln_f.bias"))},
    }


def from_hf_gpt2(hf_model, dtype=jnp.float32, compute_dtype=None
                 ) -> Tuple[TransformerConfig, dict]:
    """(config, params) from a loaded ``GPT2LMHeadModel``."""
    import dataclasses

    cfg = config_from_hf_gpt2(hf_model.config)
    cfg = dataclasses.replace(cfg, dtype=compute_dtype or dtype,
                              param_dtype=dtype)
    params = params_from_hf_gpt2(hf_model.state_dict(), cfg, dtype)
    Transformer(cfg)
    return cfg, params


def from_hf_bert(hf_model, dtype=jnp.float32, compute_dtype=None):
    """(BertConfig, params) from a ``transformers.BertForMaskedLM`` —
    the encoder-family oracle (post-LN blocks, erf-gelu, token types,
    tied MLM decoder). Same layout rules as the Llama converter: torch
    Linear weights transpose to our [in, out] kernels, per-layer leaves
    stack for the scan."""
    import dataclasses

    from tpu_on_k8s.models.bert import BertConfig

    hc = hf_model.config
    if getattr(hc, "hidden_act", "gelu") != "gelu":
        raise ValueError(f"unsupported hidden_act {hc.hidden_act!r}: this "
                         f"encoder uses the exact (erf) gelu")
    if getattr(hc, "position_embedding_type", "absolute") != "absolute":
        raise ValueError("only absolute position embeddings are supported")
    if not getattr(hc, "tie_word_embeddings", True):
        # the MLM decoder here IS the word-embedding matrix; an untied
        # checkpoint's independent decoder.weight would be silently dropped
        raise ValueError("untied MLM decoder weights are not supported "
                         "(this encoder ties the decoder to the "
                         "embeddings)")
    cfg = BertConfig(
        vocab_size=hc.vocab_size, d_model=hc.hidden_size,
        n_layers=hc.num_hidden_layers, n_heads=hc.num_attention_heads,
        d_ff=hc.intermediate_size, max_seq_len=hc.max_position_embeddings,
        type_vocab_size=hc.type_vocab_size,
        norm_eps=float(hc.layer_norm_eps))
    cfg = dataclasses.replace(cfg, dtype=compute_dtype or dtype,
                              param_dtype=dtype)
    sd = hf_model.state_dict()

    def arr(name):
        return _to_np(sd, name)

    def stacked(fmt, transpose=True):
        ws = [arr(fmt.format(i)) for i in range(cfg.n_layers)]
        return jnp.asarray(np.stack([w.T if transpose else w for w in ws]),
                           dtype)

    L = "bert.encoder.layer.{}."
    ln = lambda fmt: {"scale": stacked(fmt + ".weight", transpose=False),
                      "bias": stacked(fmt + ".bias", transpose=False)}
    dense = lambda fmt: {"kernel": stacked(fmt + ".weight"),
                         "bias": stacked(fmt + ".bias", transpose=False)}
    blocks = {
        "wq": dense(L + "attention.self.query"),
        "wk": dense(L + "attention.self.key"),
        "wv": dense(L + "attention.self.value"),
        "wo": dense(L + "attention.output.dense"),
        "attn_norm": ln(L + "attention.output.LayerNorm"),
        "w_fc": dense(L + "intermediate.dense"),
        "w_proj": dense(L + "output.dense"),
        "mlp_norm": ln(L + "output.LayerNorm"),
    }
    params = {
        "embed": jnp.asarray(arr("bert.embeddings.word_embeddings.weight"),
                             dtype),
        "pos_embed": jnp.asarray(
            arr("bert.embeddings.position_embeddings.weight"), dtype),
        "type_embed": jnp.asarray(
            arr("bert.embeddings.token_type_embeddings.weight"), dtype),
        "embed_norm": {
            "scale": jnp.asarray(arr("bert.embeddings.LayerNorm.weight"),
                                 dtype),
            "bias": jnp.asarray(arr("bert.embeddings.LayerNorm.bias"),
                                dtype)},
        "blocks": blocks,
        "mlm_transform": {
            "kernel": jnp.asarray(
                arr("cls.predictions.transform.dense.weight").T, dtype),
            "bias": jnp.asarray(
                arr("cls.predictions.transform.dense.bias"), dtype)},
        "mlm_norm": {
            "scale": jnp.asarray(
                arr("cls.predictions.transform.LayerNorm.weight"), dtype),
            "bias": jnp.asarray(
                arr("cls.predictions.transform.LayerNorm.bias"), dtype)},
        "mlm_bias": jnp.asarray(arr("cls.predictions.bias"), dtype),
    }
    return cfg, params


def quantize_serving_tree(cfg: TransformerConfig, params, *,
                          stochastic: bool = False, seed: int = 0
                          ) -> Tuple[TransformerConfig, dict]:
    """Emit the W8A16 int8 SERVING variant of a bf16/fp32 param tree:
    ``(config with serve_int8_weights=True, quantized params)`` — the
    tree ``decode.generate`` and the continuous-batching engine serve
    directly, and the variant an ``InferenceService``'s ``DecodePolicy``
    canaries against the bf16 fleet (`controller/inferenceservice.py`
    rolls it out exactly like a new image; `serve/router.py` splits the
    traffic). The int8 tree itself still has no HF state-dict form —
    ``to_hf_llama``/``to_hf_gpt2`` keep rejecting it; export the source
    checkpoint instead.

    Default rounding is the deterministic per-out-channel absmax
    round-to-nearest (`decode.quantize_weights_for_serving`).
    ``stochastic=True`` rounds through the Pallas stochastic-rounding
    kernel (`ops/quantization.py`, TPU PRNG; interpret-mode on CPU):
    unbiased in expectation, so quantization noise averages across
    channels instead of biasing them — at the price of a ``seed``
    entering the artifact."""
    import dataclasses

    from tpu_on_k8s.models.decode import quantize_weights_for_serving

    if cfg.serve_int8_weights:
        raise ValueError("param tree is already int8-serving")
    if cfg.fused_qkv or cfg.n_experts or cfg.use_bias:
        raise ValueError("int8 serving covers the unfused, bias-free, "
                         "dense layouts only (migrate the checkpoint "
                         "layout first)")
    out_cfg = dataclasses.replace(cfg, serve_int8_weights=True)
    quantizer = None
    if stochastic:
        from tpu_on_k8s.ops.quantization import quantize_int8

        def quantizer(w):
            # per-OUT-CHANNEL scales via the row-wise kernel: transpose
            # each [.., D, F] kernel to rows of length D, quantize, and
            # transpose back — kernel_q [.., D, F] + kernel_scale [.., F],
            # the exact _W8Dense param contract
            w = np.asarray(w, np.float32)
            lead, (d, f) = w.shape[:-2], w.shape[-2:]
            n = 1
            for dim in lead:
                n *= dim
            rows = w.reshape(n, d, f).transpose(0, 2, 1).reshape(n * f, d)
            vals, scales = quantize_int8(jnp.asarray(rows), seed=seed)
            q = np.asarray(vals).reshape(n, f, d).transpose(0, 2, 1)
            s = np.asarray(scales).reshape(n, f)
            return (jnp.asarray(q.reshape(*lead, d, f)),
                    jnp.asarray(s.reshape(*lead, f)))

    return out_cfg, quantize_weights_for_serving(params, quantizer)


def draft_from_hf_gpt2(hf_model, target_cfg: TransformerConfig,
                       dtype=jnp.float32, compute_dtype=None
                       ) -> Tuple[TransformerConfig, dict]:
    """(draft_cfg, draft_params) for speculative decoding beside
    ``target_cfg``: a small GPT-2 loaded through the HF interop layer
    (`from_hf_gpt2`), validated to share the target's vocabulary — the
    one property batched draft/verify needs (proposals and target
    logits index the same token space). Pass the pair straight to
    ``ContinuousBatchingEngine(draft_cfg=..., draft_params=...)``."""
    cfg, params = from_hf_gpt2(hf_model, dtype, compute_dtype)
    if cfg.vocab_size != target_cfg.vocab_size:
        raise ValueError(
            f"draft vocab {cfg.vocab_size} != target vocab "
            f"{target_cfg.vocab_size}: a speculative draft must share "
            f"the target's tokenizer")
    return cfg, params


def to_hf_llama(cfg: TransformerConfig, params) -> dict:
    """HF Llama ``state_dict`` (torch tensors) from our param tree — the
    inverse of ``params_from_hf_llama``, so a model fine-tuned here ships
    back into the transformers ecosystem. Fused training layouts
    (``wqkv``/``w_gateup``) are unfused first via the checkpoint
    migration; round-trip and exported-logit parity are pinned by
    `tests/test_hf_interop.py`."""
    import torch

    from tpu_on_k8s.models.layouts import migrate_param_layout

    if (cfg.pos_emb, cfg.norm, cfg.activation) != ("rope", "rms", "swiglu"):
        raise ValueError("to_hf_llama exports the Llama family only "
                         "(rope + rmsnorm + swiglu)")
    if cfg.use_bias or cfg.n_experts or cfg.serve_int8_weights:
        raise ValueError("biased, MoE, or int8-serving param trees have no "
                         "Llama state-dict form")
    params = migrate_param_layout(params, fused_qkv=False,
                                  fused_gateup=False)

    def t(x, transpose: bool = False):
        a = np.asarray(x, np.float32)
        return torch.tensor(a.T if transpose else a)

    b = params["blocks"]
    sd = {"model.embed_tokens.weight": t(params["embed"]),
          "model.norm.weight": t(params["final_norm"]["scale"])}
    names = [("self_attn.q_proj", b["attn"]["wq"]["kernel"]),
             ("self_attn.k_proj", b["attn"]["wk"]["kernel"]),
             ("self_attn.v_proj", b["attn"]["wv"]["kernel"]),
             ("self_attn.o_proj", b["attn"]["wo"]["kernel"]),
             ("mlp.gate_proj", b["mlp"]["w_gate"]["kernel"]),
             ("mlp.up_proj", b["mlp"]["w_up"]["kernel"]),
             ("mlp.down_proj", b["mlp"]["w_down"]["kernel"])]
    for i in range(cfg.n_layers):
        for name, stack in names:
            sd[f"model.layers.{i}.{name}.weight"] = t(stack[i],
                                                      transpose=True)
        sd[f"model.layers.{i}.input_layernorm.weight"] = t(
            b["attn_norm"]["scale"][i])
        sd[f"model.layers.{i}.post_attention_layernorm.weight"] = t(
            b["mlp_norm"]["scale"][i])
    # tied models share ONE tensor with the embedding (as HF itself ties
    # them) — duplicating would double host memory at real vocab sizes
    sd["lm_head.weight"] = (sd["model.embed_tokens.weight"]
                            if cfg.tie_embeddings
                            else t(params["lm_head"], transpose=True))
    return sd


def to_hf_gpt2(cfg: TransformerConfig, params) -> dict:
    """HF GPT-2 ``state_dict`` (torch tensors) from our param tree — the
    inverse of ``params_from_hf_gpt2`` (Conv1D keeps our [in, out] layout;
    wq/wk/wv re-fuse into c_attn)."""
    import torch

    from tpu_on_k8s.models.layouts import migrate_param_layout

    if (cfg.pos_emb, cfg.norm, cfg.activation,
            cfg.use_bias, cfg.tie_embeddings) != ("learned", "ln", "gelu",
                                                  True, True):
        raise ValueError("to_hf_gpt2 exports the GPT-2 family only "
                         "(learned positions + LayerNorm + gelu + biased "
                         "tied layout)")
    if cfg.n_kv_heads != cfg.n_heads:
        raise ValueError("HF GPT-2 has no GQA: n_kv_heads must equal "
                         "n_heads")
    if cfg.serve_int8_weights:
        raise ValueError("int8-serving param trees have no GPT-2 "
                         "state-dict form (export the bf16 checkpoint)")
    params = migrate_param_layout(params, fused_qkv=False)

    def t(x):
        return torch.tensor(np.asarray(x, np.float32))

    b = params["blocks"]
    sd = {"transformer.wte.weight": t(params["embed"]),
          "transformer.wpe.weight": t(params["pos_embed"]),
          "transformer.ln_f.weight": t(params["final_norm"]["scale"]),
          "transformer.ln_f.bias": t(params["final_norm"]["bias"])}
    # tied head: share ONE tensor with the embedding, as HF itself does
    sd["lm_head.weight"] = sd["transformer.wte.weight"]
    attn, mlp = b["attn"], b["mlp"]
    c_attn_w = np.concatenate([np.asarray(attn[n]["kernel"], np.float32)
                               for n in ("wq", "wk", "wv")], axis=-1)
    c_attn_b = np.concatenate([np.asarray(attn[n]["bias"], np.float32)
                               for n in ("wq", "wk", "wv")], axis=-1)
    for i in range(cfg.n_layers):
        L = f"transformer.h.{i}."
        sd[L + "attn.c_attn.weight"] = t(c_attn_w[i])
        sd[L + "attn.c_attn.bias"] = t(c_attn_b[i])
        sd[L + "attn.c_proj.weight"] = t(attn["wo"]["kernel"][i])
        sd[L + "attn.c_proj.bias"] = t(attn["wo"]["bias"][i])
        sd[L + "ln_1.weight"] = t(b["attn_norm"]["scale"][i])
        sd[L + "ln_1.bias"] = t(b["attn_norm"]["bias"][i])
        sd[L + "mlp.c_fc.weight"] = t(mlp["w_up"]["kernel"][i])
        sd[L + "mlp.c_fc.bias"] = t(mlp["w_up"]["bias"][i])
        sd[L + "mlp.c_proj.weight"] = t(mlp["w_down"]["kernel"][i])
        sd[L + "mlp.c_proj.bias"] = t(mlp["w_down"]["bias"][i])
        sd[L + "ln_2.weight"] = t(b["mlp_norm"]["scale"][i])
        sd[L + "ln_2.bias"] = t(b["mlp_norm"]["bias"][i])
    return sd


def from_hf_llama(hf_model, dtype=jnp.float32, compute_dtype=None
                  ) -> Tuple[TransformerConfig, dict]:
    """(config, params) from a loaded ``LlamaForCausalLM`` — ready for
    ``Transformer``, ``generate()``, the continuous-batching engine, or a
    fine-tuning ``Trainer``.

    ``dtype`` stores the converted params; ``compute_dtype`` (default:
    same as ``dtype``) sets the model's activation dtype — pass
    ``jnp.bfloat16`` for TPU serving, keep fp32 when comparing logits
    against the HF oracle bit-closely."""
    import dataclasses

    cfg = config_from_hf_llama(hf_model.config)
    cfg = dataclasses.replace(cfg, dtype=compute_dtype or dtype,
                              param_dtype=dtype)
    params = params_from_hf_llama(hf_model.state_dict(), cfg, dtype)
    Transformer(cfg)  # config constructs; bad fields fail loudly here
    return cfg, params
