"""Param-tree and cache layout descriptors/conversions (pure numpy; no
checkpoint/orbax dependency — compute-plane callers like the HF exporter
and the stdlib-only serve plane use this without dragging the
training/orchestration stack in)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np


@dataclass(frozen=True)
class CacheLayout:
    """The sharding layout a KV payload (``models/serving.KVHandoff``, a
    prefix export) CARRIES across engines — the contract that makes
    disagg prefill→decode handoff and fleet prefix reuse work across
    UNLIKE meshes:

    * **gather-on-export**: every export is host-gathered to the full
      logical array (numpy leaves hold all positions/heads, whatever
      mesh computed them), so any engine can adopt it;
    * **reshard-on-import**: the adopting engine lays the full payload
      back out under its OWN mesh (`submit_kv` / `import_prefix`) —
      the source mesh never constrains the destination.

    ``mesh_axes`` records the SOURCE engine's non-trivial mesh axes
    ({} = single-program engine) and ``gathered_bytes`` what the export
    gather moved device→host — the cross-mesh observability
    (``ShardMetrics`` export-gather accounting, the kvstore's
    cross-mesh promote counter) that says how much a reshard hop
    actually cost. Frozen/hashable: safe as a payload field and in
    event metadata."""

    mesh_axes: Dict[str, int] = field(default_factory=dict)
    gathered_bytes: int = 0

    def __post_init__(self) -> None:
        # dict fields defeat frozen hashing; store a plain dict but
        # compare/signature on the sorted items
        object.__setattr__(self, "mesh_axes", dict(self.mesh_axes))

    @property
    def sharded(self) -> bool:
        return bool(self.mesh_axes)

    def signature(self) -> str:
        """Stable string form ("" for single-device) — what unlike-mesh
        detection compares."""
        return ",".join(f"{a}={s}"
                        for a, s in sorted(self.mesh_axes.items()))

    def __hash__(self) -> int:  # dict field — hash the stable form
        return hash((self.signature(), self.gathered_bytes))


def migrate_param_layout(params: Any, *, fused_qkv: Optional[bool] = None,
                         fused_gateup: Optional[bool] = None) -> Any:
    """Convert a checkpointed param tree between the fused and unfused
    projection layouts (`tpu_on_k8s/models/transformer.py`):

    * ``fused_qkv=True`` packs ``attn/{wq,wk,wv}`` into ``attn/wqkv``
      (concatenated on the output dim, q|k|v order); ``False`` splits.
    * ``fused_gateup=True`` packs ``mlp/{w_gate,w_up}`` into
      ``mlp/w_gateup`` (gate|up order); ``False`` splits.

    The fused kernels are byte-identical concatenations of the unfused ones
    (tested in tests/test_checkpoint.py), so conversion is exact — a
    round-3 checkpoint loads into the round-4 bench config and vice versa.
    ``None`` leaves that family untouched. Works on the scan-stacked layout
    (leading ``layers`` axis) and per-layer trees alike: concatenation is
    always on the last axis.
    """
    def walk(tree: Any) -> Any:
        if not isinstance(tree, dict):
            return tree
        out = {k: walk(v) for k, v in tree.items()}
        if fused_qkv is True and {"wq", "wk", "wv"} <= set(out):
            packed = np.concatenate(
                [np.asarray(out.pop(n)["kernel"]) for n in ("wq", "wk", "wv")],
                axis=-1)
            out["wqkv"] = {"kernel": packed}
        elif fused_qkv is False and "wqkv" in out:
            k = np.asarray(out.pop("wqkv")["kernel"])
            # widths recover from the unfused heads: q is as wide as wo's
            # input; k and v split the rest evenly (GQA)
            wo_in = np.asarray(out["wo"]["kernel"]).shape[-2]
            q_w = wo_in
            kv_w = (k.shape[-1] - q_w) // 2
            out["wq"] = {"kernel": k[..., :q_w]}
            out["wk"] = {"kernel": k[..., q_w:q_w + kv_w]}
            out["wv"] = {"kernel": k[..., q_w + kv_w:]}
        if fused_gateup is True and {"w_gate", "w_up"} <= set(out):
            packed = np.concatenate(
                [np.asarray(out.pop(n)["kernel"])
                 for n in ("w_gate", "w_up")], axis=-1)
            out["w_gateup"] = {"kernel": packed}
        elif fused_gateup is False and "w_gateup" in out:
            k = np.asarray(out.pop("w_gateup")["kernel"])
            half = k.shape[-1] // 2
            out["w_gate"] = {"kernel": k[..., :half]}
            out["w_up"] = {"kernel": k[..., half:]}
        return out

    return walk(params)
