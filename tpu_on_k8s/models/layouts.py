"""Param-tree layout conversions (pure numpy; no checkpoint/orbax
dependency — compute-plane callers like the HF exporter use this without
dragging the training/orchestration stack in)."""
from __future__ import annotations

from typing import Any, Optional

import numpy as np


def migrate_param_layout(params: Any, *, fused_qkv: Optional[bool] = None,
                         fused_gateup: Optional[bool] = None) -> Any:
    """Convert a checkpointed param tree between the fused and unfused
    projection layouts (`tpu_on_k8s/models/transformer.py`):

    * ``fused_qkv=True`` packs ``attn/{wq,wk,wv}`` into ``attn/wqkv``
      (concatenated on the output dim, q|k|v order); ``False`` splits.
    * ``fused_gateup=True`` packs ``mlp/{w_gate,w_up}`` into
      ``mlp/w_gateup`` (gate|up order); ``False`` splits.

    The fused kernels are byte-identical concatenations of the unfused ones
    (tested in tests/test_checkpoint.py), so conversion is exact — a
    round-3 checkpoint loads into the round-4 bench config and vice versa.
    ``None`` leaves that family untouched. Works on the scan-stacked layout
    (leading ``layers`` axis) and per-layer trees alike: concatenation is
    always on the last axis.
    """
    def walk(tree: Any) -> Any:
        if not isinstance(tree, dict):
            return tree
        out = {k: walk(v) for k, v in tree.items()}
        if fused_qkv is True and {"wq", "wk", "wv"} <= set(out):
            packed = np.concatenate(
                [np.asarray(out.pop(n)["kernel"]) for n in ("wq", "wk", "wv")],
                axis=-1)
            out["wqkv"] = {"kernel": packed}
        elif fused_qkv is False and "wqkv" in out:
            k = np.asarray(out.pop("wqkv")["kernel"])
            # widths recover from the unfused heads: q is as wide as wo's
            # input; k and v split the rest evenly (GQA)
            wo_in = np.asarray(out["wo"]["kernel"]).shape[-2]
            q_w = wo_in
            kv_w = (k.shape[-1] - q_w) // 2
            out["wq"] = {"kernel": k[..., :q_w]}
            out["wk"] = {"kernel": k[..., q_w:q_w + kv_w]}
            out["wv"] = {"kernel": k[..., q_w + kv_w:]}
        if fused_gateup is True and {"w_gate", "w_up"} <= set(out):
            packed = np.concatenate(
                [np.asarray(out.pop(n)["kernel"])
                 for n in ("w_gate", "w_up")], axis=-1)
            out["w_gateup"] = {"kernel": packed}
        elif fused_gateup is False and "w_gateup" in out:
            k = np.asarray(out.pop("w_gateup")["kernel"])
            half = k.shape[-1] // 2
            out["w_gate"] = {"kernel": k[..., :half]}
            out["w_up"] = {"kernel": k[..., half:]}
        return out

    return walk(params)
