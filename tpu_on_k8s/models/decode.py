"""Autoregressive generation with a KV cache (the serving path).

Prefill runs the whole prompt through the decode-mode model in one call
(cache fills at positions [0, len)); each generation step then attends over
the cache with a single-token query — O(L) per token instead of O(L²). The
step loop is a ``lax.scan`` under jit, so the whole generation is one
compiled program with static shapes (cache length = ``max_seq_len``),
exactly what XLA wants on TPU.

The reference operator has no serving path beyond building an OCI image of
the trained artifact (SURVEY.md §3.5); this gives the framework an actual
inference entry point for the models it trains.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_on_k8s.models.sampling import SamplingParams, sample
from tpu_on_k8s.models.transformer import Transformer, TransformerConfig


#: kernel-holding module names converted by quantize_weights_for_serving
_W8_TARGETS = frozenset({"wq", "wk", "wv", "wo",
                         "w_gate", "w_up", "w_down", "w_gateup"})

#: The position-bucket granule AND the paged-KV page size, in tokens.
#: Every cache length the serving stack materializes — prefill buckets,
#: position-trimmed exports, disagg handoff trims, router affinity buckets,
#: and the engine's KV pages — is a multiple of this one constant, so
#: bucket boundaries and page boundaries coincide by construction. A
#: drifted copy anywhere would silently misalign exports against pages;
#: import it, never restate it. 128 is also the TPU lane width, so a page
#: is a whole number of vector tiles along the position axis.
PAGE_TOKENS = 128


def quantize_weights_for_serving(params, quantize=None) -> dict:
    """W8A16 weight conversion for ``cfg.serve_int8_weights`` serving: each
    targeted matmul kernel becomes an int8 ``kernel_q`` plus a
    per-out-channel fp32 absmax ``kernel_scale`` (the layer-scanned leading
    axis quantizes per layer). Matches the param structure the
    ``serve_int8_weights`` modules declare (`transformer._W8Dense`, the
    ``lm_head_q``/``lm_head_scale`` head); embeddings (and the tied head)
    stay full precision. Exactness: the module rescales the matmul
    product, so the only error is the int8 rounding of the kernel.

    ``quantize`` swaps the rounding scheme: it maps one kernel
    ``[..., D, F]`` to ``(int8 values [..., D, F], fp32 per-out-channel
    scales [..., F])``. Default: deterministic absmax round-to-nearest;
    `models/convert.quantize_serving_tree` passes the Pallas
    stochastic-rounding quantizer (`ops/quantization.py`) through here."""
    def absmax(w):
        w = np.asarray(w, np.float32)                   # [..., D, F]
        s = np.max(np.abs(w), axis=-2) / 127.0          # [..., F]
        s = np.maximum(s, 1e-9)
        q = np.clip(np.round(w / s[..., None, :]), -127, 127)
        return (jnp.asarray(q.astype(np.int8)),
                jnp.asarray(s.astype(np.float32)))

    quantize = quantize or absmax

    def rec(tree):
        out = {}
        for k, v in tree.items():
            if (isinstance(v, dict) and k in _W8_TARGETS
                    and set(v) == {"kernel"}):
                q, s = quantize(v["kernel"])
                out[k] = {"kernel_q": q, "kernel_scale": s}
            elif isinstance(v, dict):
                out[k] = rec(v)
            elif k == "lm_head":
                q, s = quantize(v)
                out["lm_head_q"], out["lm_head_scale"] = q, s
            else:
                out[k] = v
        return out

    return rec(params)


def truncated_draft(cfg: TransformerConfig, params,
                    n_layers: int) -> Tuple[TransformerConfig, dict]:
    """A layer-truncated self-draft for speculative decoding: the
    target's first ``n_layers`` blocks plus its own embeddings / norms /
    head (the Draft&Verify "self-speculative" shape — no second trained
    checkpoint needed, the draft is a shallow copy of the target).
    Params are layer-scanned (leading layer axis), so truncation is one
    leaf slice — no new memory beyond the views. Acceptance depends on
    how much of the target's prediction the early layers carry; the
    mechanism (and the greedy token-identity guarantee) does not."""
    if not 1 <= n_layers < cfg.n_layers:
        raise ValueError(f"draft layers must be in [1, {cfg.n_layers}), "
                         f"got {n_layers}")
    dcfg = dataclasses.replace(cfg, n_layers=n_layers)
    dparams = dict(params)
    dparams["blocks"] = jax.tree.map(lambda leaf: leaf[:n_layers],
                                     params["blocks"])
    return dcfg, dparams


def decode_model(cfg: TransformerConfig) -> Transformer:
    """The same architecture in KV-cache mode (plain attention; flash/ring
    are training-shape kernels, pointless for single-token queries)."""
    return Transformer(dataclasses.replace(
        cfg, decode=True, remat=False, attn_impl="xla"))


def cache_shapes(model: Transformer, batch: int) -> dict:
    """Abstract cache pytree shapes for a generation batch size (via
    ``eval_shape`` — no parameter initialization or tracing work)."""
    tokens = jnp.zeros((batch, 1), jnp.int32)
    shapes = jax.eval_shape(model.init, jax.random.key(0), tokens,
                            jnp.zeros((batch, 1), jnp.int32))
    return shapes["cache"]


def init_cache(model: Transformer, batch: int) -> dict:
    """Zeroed cache pytree for a given generation batch size."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        cache_shapes(model, batch))


def _bucket_len(total: int, max_seq_len: int) -> int:
    """Smallest ``PAGE_TOKENS``-multiple cache length covering ``total``
    positions, capped at the model's max. Decode is HBM-bandwidth-bound on
    cache reads, and every step attends over the WHOLE static cache — so a
    256-token request on a 1024-max model pays 4× the attention traffic it
    needs unless the cache is sized to the request. The granule doubling
    as the paged-KV page size means every bucketed export is a whole
    number of pages."""
    return min(max_seq_len,
               max(PAGE_TOKENS, -(-total // PAGE_TOKENS) * PAGE_TOKENS))


@functools.lru_cache(maxsize=32)
def _compiled_generate(cfg: TransformerConfig, b: int, lp: int,
                       max_new_tokens: int, sp: SamplingParams):
    """One compiled generation program per (config, shape) — repeated
    ``generate()`` calls (a serving loop) reuse it instead of re-tracing.
    The config is a frozen dataclass, so it keys the cache directly.

    The KV cache is allocated at the request's bucketed length, not the
    model's ``max_seq_len`` (RoPE positions are absolute, so a shorter
    cache changes nothing but the attention span — exactness is pinned by
    a parity test against the full-length cache). Learned positional
    embeddings size a parameter by ``max_seq_len``, so those models keep
    the full-length cache."""
    if cfg.pos_emb == "rope":
        cfg = dataclasses.replace(
            cfg, max_seq_len=_bucket_len(lp + max_new_tokens,
                                         cfg.max_seq_len))
    model = decode_model(cfg)
    # Abstract shapes only — the zeroed cache is materialized *inside* the
    # jitted program below, so an lru entry pins no device memory (a cached
    # full-size cache pytree per (lp, temperature) key would otherwise hold
    # ~hundreds of MB each across entries).
    shapes = cache_shapes(model, b)

    def pick(logits: jnp.ndarray, step_rng: jax.Array) -> jnp.ndarray:
        return sample(logits, step_rng, sp)

    @jax.jit
    def run(params, prompt, rng):
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        positions = jnp.broadcast_to(jnp.arange(lp), (b, lp))
        logits, upd = model.apply({"params": params, "cache": cache},
                                  prompt, positions, mutable=["cache"])
        # split once up front: reusing `rng` for both the prefill sample and
        # the scan keys would correlate the first token with later ones
        first_key, step_key = jax.random.split(rng)
        first = pick(logits[:, -1], first_key)

        def step(carry, step_rng):
            cache, tok, pos = carry
            logits, upd = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                pos[:, None], mutable=["cache"])
            nxt = pick(logits[:, -1], step_rng)
            return (upd["cache"], nxt, pos + 1), tok

        pos0 = jnp.full((b,), lp, jnp.int32)
        # each step consumes the previously generated token and emits it;
        # after max_new_tokens steps the emitted stack IS the continuation.
        _, toks = jax.lax.scan(
            step, (upd["cache"], first, pos0),
            jax.random.split(step_key, max_new_tokens))
        return toks.transpose(1, 0)

    return run


def _set_cursor(cache: dict, value) -> dict:
    """Rebuild a cache pytree with every layer's append cursor set to
    ``value``. Rolling the cursor BACK is how speculative decoding rejects
    draft tokens: stale K/V beyond the cursor is harmless because a query
    only attends to ``k_pos <= position`` and the very next append
    overwrites the first stale slot before attending."""
    def rec(d):
        return {key: (jnp.full_like(v, value) if key == "index"
                      else rec(v) if isinstance(v, dict) else v)
                for key, v in d.items()}
    return rec(cache)


@functools.lru_cache(maxsize=16)
def _compiled_speculative(cfg: TransformerConfig,
                          draft_cfg: TransformerConfig, lp: int, k: int,
                          max_total: int):
    """Three jitted programs for the speculative loop (batch 1): prefill
    both models, draft k greedy proposals, verify a k+1 chunk with the
    target. Caches are bucketed to ``max_total`` like ``generate``'s."""
    def bucketed(c):
        if c.pos_emb == "rope":
            c = dataclasses.replace(
                c, max_seq_len=_bucket_len(max_total, c.max_seq_len))
        return c

    target = decode_model(bucketed(cfg))
    draft = decode_model(bucketed(draft_cfg))
    t_shapes = cache_shapes(target, 1)
    d_shapes = cache_shapes(draft, 1)

    @jax.jit
    def prefill(params, draft_params, prompt):
        zeros = lambda s: jax.tree.map(
            lambda a: jnp.zeros(a.shape, a.dtype), s)
        positions = jnp.broadcast_to(jnp.arange(lp), (1, lp))
        t_logits, t_upd = target.apply(
            {"params": params, "cache": zeros(t_shapes)}, prompt, positions,
            mutable=["cache"])
        _, d_upd = draft.apply(
            {"params": draft_params, "cache": zeros(d_shapes)}, prompt,
            positions, mutable=["cache"])
        t0 = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)
        return t_upd["cache"], d_upd["cache"], t0

    @jax.jit
    def draft_k(draft_params, d_cache, t_last, p):
        # k+1 feeds (t_last, d_1..d_k) so the draft cache also holds d_k —
        # on full acceptance the next round appends right after it. The
        # cursor rollback (rejecting last round's unaccepted draft K/V)
        # happens HERE, under jit — one fused full_like per layer instead
        # of a host-side pytree rebuild per round.
        d_cache = _set_cursor(d_cache, p)

        def step(carry, _):
            cache, tok, pos = carry
            logits, upd = draft.apply(
                {"params": draft_params, "cache": cache}, tok[:, None],
                pos[:, None], mutable=["cache"])
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (upd["cache"], nxt, pos + 1), nxt

        (d_cache, _, _), toks = jax.lax.scan(
            step, (d_cache, t_last, jnp.full((1,), p, jnp.int32)), None,
            length=k + 1)
        return d_cache, toks[:k, 0]           # d_1..d_k (the k+1-th feed
                                              # exists only to cache d_k)

    @jax.jit
    def verify(params, t_cache, chunk, p):
        # chunk = [t_last, d_1..d_k] at positions p..p+k; greedy[i] is the
        # target's next token after chunk[:i+1]. Cursor rollback in-jit,
        # as in draft_k.
        t_cache = _set_cursor(t_cache, p)
        positions = p + jnp.arange(k + 1)[None, :]
        logits, upd = target.apply(
            {"params": params, "cache": t_cache}, chunk, positions,
            mutable=["cache"])
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return upd["cache"], greedy[0]        # [k+1]

    return prefill, draft_k, verify


def speculative_generate(cfg: TransformerConfig, params,
                         draft_cfg: TransformerConfig, draft_params,
                         prompt: jnp.ndarray, max_new_tokens: int,
                         k: int = 4) -> Tuple[jnp.ndarray, dict]:
    """Greedy speculative decoding (batch 1): a cheap draft model proposes
    ``k`` tokens per round, the target verifies them in ONE forward, and
    the longest agreeing prefix plus the target's correction token are
    emitted — matching ``generate(cfg, ...)``'s greedy output (parity
    test; exact up to fp reduction order in the batched verify forward),
    at up to (k+1)× fewer target forwards when the draft agrees. Returns
    ``(tokens [1, max_new_tokens], stats)`` where stats reports rounds
    and acceptance.

    The draft shares the target's tokenizer/vocab; both caches live at
    request-bucketed length. Cursor rollback rejects draft K/V — see
    ``_set_cursor``.
    """
    b, lp = prompt.shape
    if b != 1:
        raise ValueError("speculative_generate is batch-1 (per-row accept "
                         "counts diverge); batch requests use generate()")
    if k < 1:
        raise ValueError(f"speculation window k must be >= 1, got {k}")
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    max_total = lp + max_new_tokens + k + 1
    if max_total > cfg.max_seq_len or max_total > draft_cfg.max_seq_len:
        raise ValueError(
            f"prompt {lp} + new {max_new_tokens} + speculation window "
            f"{k + 1} exceeds max_seq_len")
    prefill, draft_k, verify = _compiled_speculative(
        cfg, draft_cfg, lp, k, max_total)
    t_cache, d_cache, t_last = prefill(params, draft_params, prompt)
    emitted = [int(t_last[0])]
    p = lp                     # position of t_last (emitted, not yet fed)
    rounds = accepted_total = 0
    while len(emitted) < max_new_tokens:
        d_cache, proposals = draft_k(draft_params, d_cache, t_last, p)
        chunk = jnp.concatenate([t_last[None, :], proposals[None, :]],
                                axis=1)                      # [1, k+1]
        t_cache, greedy = verify(params, t_cache, chunk, p)
        props = np.asarray(proposals).tolist()       # one transfer each,
        target_toks = np.asarray(greedy).tolist()    # not 2k+1 int() syncs
        j = 0
        while j < k and props[j] == target_toks[j]:
            j += 1
        emitted.extend(props[:j])
        emitted.append(target_toks[j])        # correction (or bonus at j=k)
        rounds += 1
        accepted_total += j
        p = p + j + 1                         # position of the new t_last
        t_last = greedy[j:j + 1]
    tokens = jnp.asarray(emitted[:max_new_tokens], jnp.int32)[None, :]
    stats = {"rounds": rounds, "proposed": rounds * k,
             "accepted": accepted_total,
             "acceptance_rate": (accepted_total / (rounds * k)
                                 if rounds else 0.0),
             "target_forwards": rounds + 1,
             "tokens_per_target_forward": (
                 len(emitted[:max_new_tokens]) / (rounds + 1))}
    return tokens, stats


def generate(cfg: TransformerConfig, params, prompt: jnp.ndarray,
             max_new_tokens: int, temperature: float = 0.0,
             rng: Optional[jax.Array] = None, top_k: int = 0,
             top_p: float = 0.0) -> jnp.ndarray:
    """Greedy (temperature=0) or sampled continuation of ``prompt`` [B, Lp]
    — optional top-k / nucleus filtering (`tpu_on_k8s/models/sampling.py`).

    Returns [B, max_new_tokens]. Total length must fit ``cfg.max_seq_len``.
    """
    b, lp = prompt.shape
    if lp + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt {lp} + new {max_new_tokens} exceeds max_seq_len "
            f"{cfg.max_seq_len}")
    sp = SamplingParams(temperature=temperature, top_k=top_k, top_p=top_p)
    run = _compiled_generate(cfg, b, lp, max_new_tokens, sp)
    rng = rng if rng is not None else jax.random.key(0)
    return run(params, prompt, rng)
