"""Autoregressive generation with a KV cache (the serving path).

Prefill runs the whole prompt through the decode-mode model in one call
(cache fills at positions [0, len)); each generation step then attends over
the cache with a single-token query — O(L) per token instead of O(L²). The
step loop is a ``lax.scan`` under jit, so the whole generation is one
compiled program with static shapes (cache length = ``max_seq_len``),
exactly what XLA wants on TPU.

The reference operator has no serving path beyond building an OCI image of
the trained artifact (SURVEY.md §3.5); this gives the framework an actual
inference entry point for the models it trains.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_on_k8s.models.transformer import Transformer, TransformerConfig


def decode_model(cfg: TransformerConfig) -> Transformer:
    """The same architecture in KV-cache mode (plain attention; flash/ring
    are training-shape kernels, pointless for single-token queries)."""
    return Transformer(dataclasses.replace(
        cfg, decode=True, remat=False, attn_impl="xla"))


def cache_shapes(model: Transformer, batch: int) -> dict:
    """Abstract cache pytree shapes for a generation batch size (via
    ``eval_shape`` — no parameter initialization or tracing work)."""
    tokens = jnp.zeros((batch, 1), jnp.int32)
    shapes = jax.eval_shape(model.init, jax.random.key(0), tokens,
                            jnp.zeros((batch, 1), jnp.int32))
    return shapes["cache"]


def init_cache(model: Transformer, batch: int) -> dict:
    """Zeroed cache pytree for a given generation batch size."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        cache_shapes(model, batch))


def _bucket_len(total: int, max_seq_len: int) -> int:
    """Smallest 128-multiple cache length covering ``total`` positions,
    capped at the model's max. Decode is HBM-bandwidth-bound on cache
    reads, and every step attends over the WHOLE static cache — so a
    256-token request on a 1024-max model pays 4× the attention traffic it
    needs unless the cache is sized to the request."""
    return min(max_seq_len, max(128, -(-total // 128) * 128))


@functools.lru_cache(maxsize=32)
def _compiled_generate(cfg: TransformerConfig, b: int, lp: int,
                       max_new_tokens: int, temperature: float):
    """One compiled generation program per (config, shape) — repeated
    ``generate()`` calls (a serving loop) reuse it instead of re-tracing.
    The config is a frozen dataclass, so it keys the cache directly.

    The KV cache is allocated at the request's bucketed length, not the
    model's ``max_seq_len`` (RoPE positions are absolute, so a shorter
    cache changes nothing but the attention span — exactness is pinned by
    a parity test against the full-length cache). Learned positional
    embeddings size a parameter by ``max_seq_len``, so those models keep
    the full-length cache."""
    if cfg.pos_emb == "rope":
        cfg = dataclasses.replace(
            cfg, max_seq_len=_bucket_len(lp + max_new_tokens,
                                         cfg.max_seq_len))
    model = decode_model(cfg)
    # Abstract shapes only — the zeroed cache is materialized *inside* the
    # jitted program below, so an lru entry pins no device memory (a cached
    # full-size cache pytree per (lp, temperature) key would otherwise hold
    # ~hundreds of MB each across entries).
    shapes = cache_shapes(model, b)

    def pick(logits: jnp.ndarray, step_rng: jax.Array) -> jnp.ndarray:
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            step_rng, logits / temperature, axis=-1).astype(jnp.int32)

    @jax.jit
    def run(params, prompt, rng):
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        positions = jnp.broadcast_to(jnp.arange(lp), (b, lp))
        logits, upd = model.apply({"params": params, "cache": cache},
                                  prompt, positions, mutable=["cache"])
        # split once up front: reusing `rng` for both the prefill sample and
        # the scan keys would correlate the first token with later ones
        first_key, step_key = jax.random.split(rng)
        first = pick(logits[:, -1], first_key)

        def step(carry, step_rng):
            cache, tok, pos = carry
            logits, upd = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                pos[:, None], mutable=["cache"])
            nxt = pick(logits[:, -1], step_rng)
            return (upd["cache"], nxt, pos + 1), tok

        pos0 = jnp.full((b,), lp, jnp.int32)
        # each step consumes the previously generated token and emits it;
        # after max_new_tokens steps the emitted stack IS the continuation.
        _, toks = jax.lax.scan(
            step, (upd["cache"], first, pos0),
            jax.random.split(step_key, max_new_tokens))
        return toks.transpose(1, 0)

    return run


def generate(cfg: TransformerConfig, params, prompt: jnp.ndarray,
             max_new_tokens: int, temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Greedy (temperature=0) or sampled continuation of ``prompt`` [B, Lp].

    Returns [B, max_new_tokens]. Total length must fit ``cfg.max_seq_len``.
    """
    b, lp = prompt.shape
    if lp + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt {lp} + new {max_new_tokens} exceeds max_seq_len "
            f"{cfg.max_seq_len}")
    run = _compiled_generate(cfg, b, lp, max_new_tokens, temperature)
    rng = rng if rng is not None else jax.random.key(0)
    return run(params, prompt, rng)
