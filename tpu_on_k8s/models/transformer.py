"""Flagship decoder-only transformer (Llama-family), TPU-first.

Design choices driven by the hardware, not by any reference code (the
reference operator contains no model code — training math lived in user
containers, SURVEY.md §2.10):

* **scan over layers** (``nn.scan``): one compiled block body regardless of
  depth — compile time and HLO size are O(1) in ``n_layers``; parameters are
  stacked on a leading layer axis.
* **bf16 compute, fp32 params**: matmuls hit the MXU in bf16; RMSNorm/softmax
  statistics accumulate in fp32.
* **GQA + RoPE**, SwiGLU MLP — the Llama-2/3 shape, so the 7B benchmark
  config maps 1:1.
* **pluggable attention**: ``attn_impl`` selects plain XLA attention, the
  Pallas flash kernel (`tpu_on_k8s/ops/flash_attention.py`), or ring
  attention over the mesh ``seq`` axis (`tpu_on_k8s/parallel/ring.py`).
* **remat** (``jax.checkpoint``) per block, trading FLOPs for HBM.

Sharding is *external*: `flagship_partition_rules()` returns the
megatron-layout rule list (fsdp on one matmul dim, model/tensor on the
other) consumed by `tpu_on_k8s/parallel/partition.py`.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_on_k8s.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEQ,
)
from tpu_on_k8s.parallel.partition import PartitionRule


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16          # compute dtype (MXU-friendly)
    param_dtype: Any = jnp.float32     # master weights
    remat: bool = True
    remat_policy: str = "full"         # "full" | "dots" (save MXU outputs)
                                       # | "dots_kernels" (dots + pallas-call
                                       #   outputs: flash o/lse stay resident,
                                       #   so the bwd pass never re-runs the
                                       #   attention forward kernel)
                                       # | "mlp" (remat only the MLP)
    attn_impl: str = "xla"             # "xla" | "flash" | "ring" | "ulysses"
    attn_block_q: int = 0              # flash kernel q-block; 0 = auto (512)
    attn_block_k: int = 0              # flash kernel k-block; 0 = auto (512)
    scan_unroll: int = 1               # layers unrolled per scan iteration
                                       # (trades compile time/HLO size for
                                       # less loop bookkeeping per step)
    attn_native_gqa: bool = False      # flash path: feed Hkv-head k/v to the
                                       # kernel (no HBM repeat; halves attn
                                       # residual memory). Measured ~1%
                                       # SLOWER at the 350M/seq-1024 bench
                                       # (the dkv accumulation grid costs
                                       # more than the repeats saved) but
                                       # wins when K/V memory dominates
                                       # (long context / tight HBM).
    fused_qkv: bool = False            # one [D, (H+2Hkv)·Dh] projection
                                       # matmul instead of three — a larger
                                       # MXU tile and one pass over x
                                       # (heads-leading path only; param
                                       # lives at attn/wqkv/kernel)
    mlp_int8: bool = False             # int8-forward MLP matmuls (SwitchBack
                                       # scheme, `tpu_on_k8s/ops/int8_matmul`):
                                       # s8×s8→s32 on the MXU at 2× the bf16
                                       # rate, bf16 backward. Opt-in: trades
                                       # forward quantization noise for
                                       # throughput.
    int8_impl: str = "xla"             # "xla" (dot_general + fused-by-XLA
                                       # dequant) | "pallas" (one kernel:
                                       # int32 tile accumulator rescaled in
                                       # VMEM, no HBM round trip)
    mlp_fused_gateup: bool = False     # one [D, 2·d_ff] matmul for SwiGLU's
                                       # gate+up (param mlp/w_gateup/kernel):
                                       # the activation is read/quantized
                                       # once and the MXU tile doubles.
    head_int8: bool = False            # int8-forward lm_head matmul (fp32
                                       # logits out; adds ~0.8% relative
                                       # quantization noise to logits)
    attn_int8: bool = False            # int8-forward attention projections
                                       # (qkv/out); costs one layout
                                       # transpose per tensor vs the
                                       # heads-leading bf16 einsum.
                                       # Heads-leading path only (xla/flash
                                       # train): decode and ring/ulysses
                                       # keep bf16 projections by design
                                       # (serving precision; skinny decode
                                       # matmuls gain nothing from int8)
    serve_int8_weights: bool = False   # serving (decode-only): weights are
                                       # int8 with per-out-channel fp32
                                       # scales (W8A16,
                                       # `decode.quantize_weights_for_serving`)
                                       # — the bandwidth-bound decode loop
                                       # reads ~half the weight bytes; the
                                       # product rescale is exact
                                       # per-channel math, quantization
                                       # noise only from the int8 rounding.
    cache_int8: bool = False           # serving: store the KV cache int8
                                       # with per-(token, head) fp32 scales
                                       # — ~half the cache HBM traffic in
                                       # the bandwidth-bound decode loop.
                                       # Dequant fuses into the attention
                                       # read. Opt-in (lossy: absmax/127
                                       # per-vector quantization noise).
    pos_emb: str = "rope"              # "rope" | "learned" (GPT-2 family)
    norm: str = "rms"                  # "rms" | "ln"
    activation: str = "swiglu"         # "swiglu" | "gelu"
    use_bias: bool = False             # biases on attention/MLP projections
                                       # (GPT-2/BERT-family faithfulness;
                                       # Llama family runs bias-free)
    tie_embeddings: bool = False       # lm_head = embed^T (GPT-2/BERT style)
    n_experts: int = 0                 # >0: MoE MLP (tpu_on_k8s/models/moe.py)
    experts_top_k: int = 2
    expert_capacity_factor: float = 1.25
    decode: bool = False               # KV-cache autoregressive mode
    decode_multislot: bool = False     # continuous-batching serving: the
                                       # cache batch dim is a SLOT pool with
                                       # per-row positions (no shared
                                       # cursor); appends scatter at each
                                       # row's position and out-of-bounds
                                       # positions (the free-slot sentinel)
                                       # are dropped. Requests at different
                                       # progress share one compiled step
                                       # (`tpu_on_k8s/models/serving.py`).

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    # ---- named sizes ---------------------------------------------------------
    @staticmethod
    def llama2_7b() -> "TransformerConfig":
        return TransformerConfig()  # defaults are the 7B shape

    @staticmethod
    def gpt2_small() -> "TransformerConfig":
        """The 124M GPT-2 shape (BASELINE.json elastic benchmark config)."""
        return TransformerConfig(vocab_size=50257, d_model=768, n_layers=12,
                                 n_heads=12, n_kv_heads=12, d_ff=3072,
                                 max_seq_len=1024, pos_emb="learned",
                                 norm="ln", activation="gelu",
                                 tie_embeddings=True)

    @staticmethod
    def llama2_1b() -> "TransformerConfig":
        return TransformerConfig(d_model=2048, n_layers=16, n_heads=16,
                                 n_kv_heads=8, d_ff=5632)

    @staticmethod
    def tiny() -> "TransformerConfig":
        """Test/dry-run shape: every sharded dim divisible by an 8-way mesh."""
        return TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                 n_heads=4, n_kv_heads=2, d_ff=128,
                                 max_seq_len=128, remat=False)


def _int8_mm(impl: str):
    """The int8-forward matmul for ``cfg.int8_impl`` — shared by every int8
    call site (MLP, attention projections, lm head). The batched MoE path
    stays XLA (no batched Pallas kernel)."""
    from tpu_on_k8s.ops.int8_matmul import int8_matmul, int8_matmul_pallas
    if impl == "pallas":
        return int8_matmul_pallas
    if impl != "xla":
        raise ValueError(f"unknown int8_impl {impl!r} (use 'xla'|'pallas')")
    return int8_matmul


def _dots_and_kernels_saveable(prim, *args, **params) -> bool:
    """Checkpoint policy: no-batch-dim dots + Pallas kernel outputs saveable."""
    if prim is None:
        return False
    if getattr(prim, "name", "") == "pallas_call":
        return True
    return jax.checkpoint_policies.dots_with_no_batch_dims_saveable(
        prim, *args, **params)


def _rope_tables(positions: jnp.ndarray, half: int, theta: float):
    """cos/sin tables [B, L, half] shared by both rope layouts."""
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, L, half]
    return jnp.cos(angles), jnp.sin(angles)


def _rope_rotate(x: jnp.ndarray, cos: jnp.ndarray,
                 sin: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary position embedding. x: [B, L, H, Dh]; positions: [B, L]."""
    cos, sin = _rope_tables(positions, x.shape[-1] // 2, theta)
    return _rope_rotate(x, cos[:, :, None, :], sin[:, :, None, :])


def xla_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  causal: bool = True,
                  segments: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain attention, letting XLA fuse; softmax statistics in fp32.

    q: [B, L, H, Dh]; k/v: [B, L, H, Dh] (kv already repeated to H heads).
    ``segments [B, L]``: attend only within the same segment (packed
    windows; also expresses key-padding masks via sentinel segments).
    """
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k,
                        preferred_element_type=jnp.float32) * scale
    l, m = logits.shape[-2], logits.shape[-1]
    mask = jnp.ones((1, 1, l, m), dtype=bool)
    if causal:
        mask = mask & jnp.tril(jnp.ones((l, m), dtype=bool))
    if segments is not None:
        mask = mask & (segments[:, None, :, None]
                       == segments[:, None, None, :])
    if causal or segments is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def xla_attention_bhld(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                       causal: bool = True,
                       segments: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """``xla_attention`` for heads-leading [B, H, L, Dh] tensors.

    ``segments [B, L]``: packed-window attention — a query attends only
    within its own segment (block-diagonal ∧ causal), so documents packed
    into one training window never leak attention across boundaries."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhld,bhmd->bhlm", q, k,
                        preferred_element_type=jnp.float32) * scale
    l, m = logits.shape[-2], logits.shape[-1]
    mask = jnp.ones((1, 1, l, m), dtype=bool)
    if causal:
        mask = mask & jnp.tril(jnp.ones((l, m), dtype=bool))
    if segments is not None:
        mask = mask & (segments[:, None, :, None]
                       == segments[:, None, None, :])
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bhmd->bhld", probs, v)


def _select_attention(impl: str) -> Callable[..., jnp.ndarray]:
    if impl == "xla":
        return xla_attention
    if impl == "flash":
        try:
            from tpu_on_k8s.ops.flash_attention import flash_attention
        except ImportError as e:
            raise NotImplementedError(
                "attn_impl='flash' requires tpu_on_k8s.ops.flash_attention") from e
        return flash_attention
    if impl == "ring":
        try:
            from tpu_on_k8s.parallel.ring import ring_attention
        except ImportError as e:
            raise NotImplementedError(
                "attn_impl='ring' requires tpu_on_k8s.parallel.ring") from e
        return ring_attention
    if impl == "ulysses":
        try:
            from tpu_on_k8s.parallel.ulysses import ulysses_attention
        except ImportError as e:
            raise NotImplementedError(
                "attn_impl='ulysses' requires tpu_on_k8s.parallel.ulysses") from e
        return ulysses_attention
    raise ValueError(f"unknown attn_impl {impl!r}")


class RMSNorm(nn.Module):
    eps: float
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           self.param_dtype)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.eps)
        return (y * scale.astype(jnp.float32)).astype(self.dtype)


def make_norm(cfg: TransformerConfig, name: str) -> nn.Module:
    if cfg.norm == "ln":
        return nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype, name=name)
    return RMSNorm(cfg.norm_eps, cfg.dtype, cfg.param_dtype, name=name)


def rope_bhld(x: jnp.ndarray, positions: jnp.ndarray,
              theta: float) -> jnp.ndarray:
    """Rotary embedding for heads-leading x: [B, H, L, Dh]; positions [B, L]."""
    cos, sin = _rope_tables(positions, x.shape[-1] // 2, theta)
    return _rope_rotate(x, cos[:, None, :, :], sin[:, None, :, :])


class _HeadProj(nn.Module):
    """QKV projection emitting heads-leading [B, H, L, Dh] straight from the
    matmul (``bld,dhf->bhlf``) — no transpose op between projection and
    attention kernel. The param is the identical 2-D ``kernel`` an
    ``nn.Dense`` would own (reshaped on the fly, a free relayout), keeping
    checkpoints and partition rules layout-agnostic.

    ``int8=True`` runs the int8-forward path as a 2-D matmul plus an
    explicit [B,L,H,Dh]→[B,H,L,Dh] transpose (the einsum's implicit
    relayout can't fold into the quantized dot)."""

    heads: int
    head_dim: int
    dtype: Any
    param_dtype: Any
    int8: bool = False
    int8_impl: str = "xla"
    use_bias: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        d_in = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.normal(0.02),
                            (d_in, self.heads * self.head_dim),
                            self.param_dtype)
        if self.int8:
            b, l = x.shape[0], x.shape[1]
            y = _int8_mm(self.int8_impl)(x, kernel.astype(self.dtype))
            return y.reshape(b, l, self.heads,
                             self.head_dim).transpose(0, 2, 1, 3)
        k3 = kernel.reshape(d_in, self.heads, self.head_dim).astype(self.dtype)
        out = jnp.einsum("bld,dhf->bhlf", x, k3)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (self.heads * self.head_dim,),
                              self.param_dtype)
            out = out + bias.reshape(self.heads, 1,
                                     self.head_dim).astype(self.dtype)
        return out


class _FusedQKVProj(nn.Module):
    """Single QKV projection: one ``[D, (H+2·Hkv)·Dh]`` kernel, one matmul,
    sliced into heads-leading q/k/v on the (cheap) head axis. Feeds the MXU
    a 2× wider tile than three separate projections and reads the activation
    from HBM once instead of three times."""

    heads: int
    kv_heads: int
    head_dim: int
    dtype: Any
    param_dtype: Any
    int8: bool = False
    int8_impl: str = "xla"

    @nn.compact
    def __call__(self, x: jnp.ndarray):
        d_in = x.shape[-1]
        total = self.heads + 2 * self.kv_heads
        kernel = self.param("kernel", nn.initializers.normal(0.02),
                            (d_in, total * self.head_dim), self.param_dtype)
        if self.int8:
            b, l = x.shape[0], x.shape[1]
            y = _int8_mm(self.int8_impl)(x, kernel.astype(self.dtype))
            qkv = y.reshape(b, l, total, self.head_dim).transpose(0, 2, 1, 3)
        else:
            k3 = kernel.reshape(d_in, total,
                                self.head_dim).astype(self.dtype)
            qkv = jnp.einsum("bld,dhf->bhlf", x, k3)   # [B, H+2Hkv, L, Dh]
        h, hk = self.heads, self.kv_heads
        return qkv[:, :h], qkv[:, h:h + hk], qkv[:, h + hk:]


class _OutProj(nn.Module):
    """Output projection consuming heads-leading [B, H, L, Dh]
    (``bhlf,hfd->bld``); param identical to the ``nn.Dense`` wo kernel."""

    d_model: int
    heads: int
    head_dim: int
    dtype: Any
    param_dtype: Any
    int8: bool = False
    int8_impl: str = "xla"
    use_bias: bool = False

    @nn.compact
    def __call__(self, o: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param("kernel", nn.initializers.normal(0.02),
                            (self.heads * self.head_dim, self.d_model),
                            self.param_dtype)
        if self.int8:
            b, h, l, f = o.shape
            flat = o.transpose(0, 2, 1, 3).reshape(b, l, h * f)
            return _int8_mm(self.int8_impl)(flat, kernel.astype(self.dtype))
        k3 = kernel.reshape(self.heads, self.head_dim,
                            self.d_model).astype(self.dtype)
        out = jnp.einsum("bhlf,hfd->bld", o, k3)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (self.d_model,), self.param_dtype)
            out = out + bias.astype(self.dtype)
        return out


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, positions: jnp.ndarray,
                 segments: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        cfg = self.cfg
        if segments is not None and (cfg.decode
                                     or cfg.attn_impl not in ("xla",
                                                              "flash")):
            raise ValueError("segment-masked attention is a packed-window "
                             "TRAINING feature (xla/flash paths only)")
        if cfg.serve_int8_weights:
            dense = lambda feats, name: _W8Dense(feats, name=name,
                                                 dtype=cfg.dtype)
        else:
            dense = lambda feats, name: nn.Dense(
                feats, use_bias=cfg.use_bias, name=name, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.initializers.normal(0.02))
        if cfg.attn_impl in ("xla", "flash") and not cfg.decode:
            return self._attention_bhld(x, positions, segments)
        b, l = x.shape[0], x.shape[1]
        if cfg.fused_qkv:
            # same wqkv param as the heads-leading path, so fused-qkv
            # checkpoints serve (decode) and ring/ulysses-train unchanged
            qh, kh, vh = _FusedQKVProj(cfg.n_heads, cfg.n_kv_heads,
                                       cfg.head_dim, cfg.dtype,
                                       cfg.param_dtype, name="wqkv")(x)
            q, k, v = (t.transpose(0, 2, 1, 3) for t in (qh, kh, vh))
        else:
            q = dense(cfg.n_heads * cfg.head_dim, "wq")(x)
            k = dense(cfg.n_kv_heads * cfg.head_dim, "wk")(x)
            v = dense(cfg.n_kv_heads * cfg.head_dim, "wv")(x)
            q = q.reshape(b, l, cfg.n_heads, cfg.head_dim)
            k = k.reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
            v = v.reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        if cfg.pos_emb == "rope":
            q = rope(q, positions, cfg.rope_theta)
            k = rope(k, positions, cfg.rope_theta)
        # GQA: repeat kv groups up to n_heads before the kernel; XLA folds the
        # broadcast into the einsum so no HBM copy materialises.
        rep = cfg.n_heads // cfg.n_kv_heads
        if cfg.decode:
            out = self._cached_attention(q, k, v, positions, rep)
        else:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
            out = _select_attention(cfg.attn_impl)(q, k, v, causal=True)
        out = out.reshape(b, l, cfg.n_heads * cfg.head_dim)
        return dense(cfg.d_model, "wo")(out)

    def _attention_bhld(self, x: jnp.ndarray, positions: jnp.ndarray,
                        segments: Optional[jnp.ndarray] = None
                        ) -> jnp.ndarray:
        """Heads-leading fast path for the single-device attention impls
        (measured ~35% faster per layer than project→reshape→transpose at
        the 350M bench shape; see `_HeadProj`)."""
        cfg = self.cfg
        if cfg.fused_qkv:
            q, k, v = _FusedQKVProj(cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
                                    cfg.dtype, cfg.param_dtype,
                                    int8=cfg.attn_int8,
                                    int8_impl=cfg.int8_impl, name="wqkv")(x)
        else:
            hp = lambda heads, name: _HeadProj(heads, cfg.head_dim, cfg.dtype,
                                               cfg.param_dtype,
                                               int8=cfg.attn_int8,
                                               int8_impl=cfg.int8_impl,
                                               use_bias=cfg.use_bias,
                                               name=name)
            q = hp(cfg.n_heads, "wq")(x)          # [B, H, L, Dh]
            k = hp(cfg.n_kv_heads, "wk")(x)       # [B, Hkv, L, Dh]
            v = hp(cfg.n_kv_heads, "wv")(x)
        if cfg.pos_emb == "rope":
            q = rope_bhld(q, positions, cfg.rope_theta)
            k = rope_bhld(k, positions, cfg.rope_theta)
        if cfg.attn_impl == "flash":
            from tpu_on_k8s.ops.flash_attention import (
                _flash,
                _flash_seg,
                auto_block,
                padded_len,
            )
            l = q.shape[2]
            # Ragged lengths (no legal 128-block) stay on the Pallas path:
            # zero-pad the tail, mask the padded keys in-kernel, slice the
            # padded query rows off — exact at any length, and ~(lp/l−1)
            # extra FLOPs instead of the XLA-attention fallback cliff
            # (round 4 measured seq 4000 at 2.5× the 4096 step time).
            lp = padded_len(l)
            if lp != l:
                pad = [(0, 0), (0, 0), (0, lp - l), (0, 0)]
                q = jnp.pad(q, pad)
                k = jnp.pad(k, pad)
                v = jnp.pad(v, pad)
                if segments is not None:
                    # pad rows get a sentinel segment; outputs sliced off
                    segments = jnp.pad(segments, [(0, 0), (0, lp - l)],
                                       constant_values=-1)
            bq = cfg.attn_block_q or auto_block(lp)
            bk = cfg.attn_block_k or auto_block(lp)
            if not cfg.attn_native_gqa:
                rep = cfg.n_heads // cfg.n_kv_heads
                k = jnp.repeat(k, rep, axis=1)
                v = jnp.repeat(v, rep, axis=1)
            # else: the kernel's index maps route q-head → kv group natively
            valid = l if lp != l else 0
            if segments is not None:
                # packed windows stay on the kernel: segments ride as an
                # int operand and mask in-VMEM (block-diagonal ∧ causal)
                out = _flash_seg(q, k, v, segments.astype(jnp.int32),
                                 True, bq, bk, valid)
            else:
                out = _flash(q, k, v, True, bq, bk, valid)
            if lp != l:
                out = out[:, :, :l]
        else:
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
            out = xla_attention_bhld(q, k, v, causal=True,
                                     segments=segments)
        return _OutProj(cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.dtype,
                        cfg.param_dtype, int8=cfg.attn_int8,
                        int8_impl=cfg.int8_impl, use_bias=cfg.use_bias,
                        name="wo")(out)

    def _cached_attention(self, q, k, v, positions, rep: int) -> jnp.ndarray:
        """KV-cache attention: append this call's keys/values at the cache
        cursor, attend over every cached position ≤ the query position.
        Serves both prefill (L>1) and single-token steps (L=1).

        Prefill (a multi-token call into an empty cache — how ``generate``
        always starts) attends only among the L prompt tokens instead of
        over the full ``max_seq_len`` cache: O(L²/2) masked work instead of
        O(L·max), via the flash kernel when L has a legal block. The cache
        still fills so the scan steps that follow see every prompt
        position."""
        cfg = self.cfg
        b, l = q.shape[0], q.shape[1]
        shape = (b, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim)
        if cfg.cache_int8:
            # int8 cache + per-(token, head) fp32 absmax scales: the decode
            # loop reads ~half the bytes per step; dequant is elementwise
            # and fuses into the attention read. Quantization happens once
            # at append time, so prefill writes are quantized exactly like
            # step writes (every later step sees the same cache either way).
            ck = self.variable("cache", "k", jnp.zeros, shape, jnp.int8)
            cv = self.variable("cache", "v", jnp.zeros, shape, jnp.int8)
            cks = self.variable("cache", "k_scale", jnp.zeros, shape[:3],
                                jnp.float32)
            cvs = self.variable("cache", "v_scale", jnp.zeros, shape[:3],
                                jnp.float32)
        else:
            ck = self.variable("cache", "k", jnp.zeros, shape, k.dtype)
            cv = self.variable("cache", "v", jnp.zeros, shape, v.dtype)
        def quantize(x):
            s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
            safe = jnp.maximum(s, 1e-9)
            q8 = jnp.round(x.astype(jnp.float32) / safe[..., None])
            return q8.astype(jnp.int8), s.astype(jnp.float32)

        if cfg.decode_multislot:
            # Continuous batching: every row is an independent slot at its
            # own position, so appends scatter at `positions` per row
            # instead of a shared cursor. mode="drop" makes the free-slot
            # sentinel (position == max_seq_len, out of bounds) a no-op
            # write; stale K/V beyond a slot's position is never attended
            # (queries mask to k_pos <= position) and is overwritten before
            # the position ever reaches it.
            rows = jnp.arange(b, dtype=jnp.int32)[:, None]
            if cfg.cache_int8:
                k8, ks = quantize(k)
                v8, vs = quantize(v)
                ck.value = ck.value.at[rows, positions].set(k8, mode="drop")
                cv.value = cv.value.at[rows, positions].set(v8, mode="drop")
                cks.value = cks.value.at[rows, positions].set(ks,
                                                             mode="drop")
                cvs.value = cvs.value.at[rows, positions].set(vs,
                                                              mode="drop")
            else:
                ck.value = ck.value.at[rows, positions].set(k, mode="drop")
                cv.value = cv.value.at[rows, positions].set(v, mode="drop")
        else:
            cursor = self.variable("cache", "index",
                                   lambda: jnp.zeros((), jnp.int32))
            start = cursor.value
            if cfg.cache_int8:
                k8, ks = quantize(k)
                v8, vs = quantize(v)
                ck.value = jax.lax.dynamic_update_slice(ck.value, k8,
                                                        (0, start, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(cv.value, v8,
                                                        (0, start, 0, 0))
                cks.value = jax.lax.dynamic_update_slice(cks.value, ks,
                                                         (0, start, 0))
                cvs.value = jax.lax.dynamic_update_slice(cvs.value, vs,
                                                         (0, start, 0))
            else:
                ck.value = jax.lax.dynamic_update_slice(ck.value, k,
                                                        (0, start, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(cv.value, v,
                                                        (0, start, 0, 0))
            cursor.value = start + l

        def cached_kv():
            if cfg.cache_int8:
                kd = ck.value.astype(jnp.float32) * cks.value[..., None]
                vd = cv.value.astype(jnp.float32) * cvs.value[..., None]
                return kd.astype(k.dtype), vd.astype(v.dtype)
            return ck.value, cv.value

        def over_cache(_):
            """Attend over the whole cache, masked to ≤ query position —
            correct for any cursor (chunked prefill, single-token steps)."""
            kc, vc = cached_kv()
            k_all = jnp.repeat(kc, rep, axis=2)          # [B, max, H, Dh]
            v_all = jnp.repeat(vc, rep, axis=2)
            scale = cfg.head_dim ** -0.5
            logits = jnp.einsum("blhd,bmhd->bhlm",
                                q.astype(jnp.float32) * scale,
                                k_all.astype(jnp.float32),
                                preferred_element_type=jnp.float32)
            k_pos = jnp.arange(cfg.max_seq_len)
            mask = k_pos[None, None, None, :] <= positions[:, None, :, None]
            probs = jax.nn.softmax(
                jnp.where(mask, logits, -1e30), axis=-1).astype(q.dtype)
            return jnp.einsum("bhlm,bmhd->blhd", probs, v_all)

        if l == 1 or cfg.decode_multislot:
            # multislot rows sit at unrelated positions — the among-prompt
            # fast path's shared causal mask can never apply
            return over_cache(None)

        def among_prompt(_):
            """Empty-cache prefill (how ``generate`` always starts): attend
            causally among the L prompt tokens only — O(L²/2) instead of
            O(L·max). On an accelerator backend the flash kernel serves it
            when L has a legal block (Hkv-head k/v fed natively, no repeat
            materialized); on CPU the XLA einsum stays faster than Pallas
            interpret mode."""
            use_flash = jax.default_backend() != "cpu"
            if use_flash:
                try:
                    from tpu_on_k8s.ops.flash_attention import flash_attention
                except ImportError:
                    use_flash = False
            if use_flash:
                # any length is legal: flash_attention pads-and-masks ragged
                # prompt lengths internally
                return flash_attention(q, k, v, causal=True)
            return xla_attention(q, jnp.repeat(k, rep, axis=2),
                                 jnp.repeat(v, rep, axis=2), causal=True)

        # Both branches compile; the condition picks at run time, so chunked
        # appends into a non-empty cache — or a fresh prefill whose
        # positions are NOT the plain arange the causal mask assumes (e.g.
        # clamped pad positions) — stay on the exact over-cache semantics.
        fresh = jnp.logical_and(
            start == 0,
            jnp.all(positions == jnp.arange(l, dtype=positions.dtype)[None]))
        return jax.lax.cond(fresh, among_prompt, over_cache, None)


class _Int8Dense(nn.Module):
    """``nn.Dense`` twin whose matmul runs the int8-forward path. The param
    is the identical 2-D ``kernel`` (same name/shape/partition rules), so
    ``mlp_int8`` can be flipped on a checkpoint without conversion."""

    features: int
    dtype: Any
    param_dtype: Any
    impl: str = "xla"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param("kernel", nn.initializers.normal(0.02),
                            (x.shape[-1], self.features), self.param_dtype)
        return _int8_mm(self.impl)(x, kernel.astype(self.dtype))


class _W8Dense(nn.Module):
    """Serving-time W8A16 dense: an int8 kernel plus a per-out-channel fp32
    scale (produced by ``decode.quantize_weights_for_serving`` — init values
    are placeholders for structure only). The matmul reads int8 weights
    from HBM (XLA fuses the widening convert into the dot operand) and
    rescales the PRODUCT — ``x @ (q·s) == (x @ q)·s`` for a per-column
    scale, so no dequantized kernel is ever materialized."""

    features: int
    dtype: Any

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        q = self.param("kernel_q", nn.initializers.zeros_init(),
                       (x.shape[-1], self.features), jnp.int8)
        s = self.param("kernel_scale", nn.initializers.ones_init(),
                       (self.features,), jnp.float32)
        y = jnp.einsum("...d,df->...f", x, q.astype(self.dtype))
        # rescale in fp32 (a bf16-rounded scale would add ~0.4% systematic
        # per-channel error); the only rounding left is the final cast back
        return (y.astype(jnp.float32) * s).astype(self.dtype)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        if cfg.serve_int8_weights:
            dense = lambda feats, name: _W8Dense(feats, name=name,
                                                 dtype=cfg.dtype)
        elif cfg.mlp_int8:
            dense = lambda feats, name: _Int8Dense(
                feats, name=name, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                impl=cfg.int8_impl)
        else:
            dense = lambda feats, name: nn.Dense(
                feats, use_bias=cfg.use_bias, name=name, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.initializers.normal(0.02))
        if cfg.activation == "gelu":
            return dense(cfg.d_model, "w_down")(nn.gelu(dense(cfg.d_ff, "w_up")(x)))
        if cfg.mlp_fused_gateup:
            gu = dense(2 * cfg.d_ff, "w_gateup")(x)
            gate, up = gu[..., :cfg.d_ff], gu[..., cfg.d_ff:]
        else:
            gate = dense(cfg.d_ff, "w_gate")(x)
            up = dense(cfg.d_ff, "w_up")(x)
        return dense(cfg.d_model, "w_down")(nn.silu(gate) * up)


class Block(nn.Module):
    """Pre-norm block; returns a (carry, None) pair so it can be nn.scan'd."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, positions: jnp.ndarray,
                 segments: Optional[jnp.ndarray] = None):
        cfg = self.cfg
        h = x + Attention(cfg, name="attn")(
            make_norm(cfg, "attn_norm")(x), positions, segments)
        if cfg.n_experts > 0:
            from tpu_on_k8s.models.moe import MoEMLP
            if cfg.remat and cfg.remat_policy == "mlp":
                mlp = nn.remat(
                    MoEMLP, prevent_cse=False,
                    policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                )(cfg, name="moe")
            else:
                mlp = MoEMLP(cfg, name="moe")
        elif cfg.remat and cfg.remat_policy == "mlp":
            # MLP-only remat: the d_ff activations (the big buffers) are
            # recomputed, while attention residuals (q/k/v/o/lse — small once
            # flash attention removes the L² scores) stay resident so the
            # backward pass never re-runs the attention forward kernel.
            mlp = nn.remat(
                MLP, prevent_cse=False,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )(cfg, name="mlp")
        else:
            mlp = MLP(cfg, name="mlp")
        out = h + mlp(make_norm(cfg, "mlp_norm")(h))
        return out, None


class Transformer(nn.Module):
    """Decoder-only LM. __call__([B, L] int tokens) → [B, L, vocab] logits.

    ``apply(..., method="features")`` returns the final-norm hidden states
    [B, L, D] plus the output-projection matrix [D, V] instead of logits, so
    a chunked loss (`tpu_on_k8s/train/trainer.py::chunked_cross_entropy`) can
    fold the head matmul into per-chunk loss computation and never
    materialise the [B, L, V] fp32 logits in HBM.
    """

    cfg: TransformerConfig

    def features(self, tokens: jnp.ndarray,
                 positions: Optional[jnp.ndarray] = None,
                 segments: Optional[jnp.ndarray] = None):
        x, head = self._trunk(tokens, positions, segments)
        return x, head

    def __call__(self, tokens: jnp.ndarray,
                 positions: Optional[jnp.ndarray] = None,
                 segments: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        x, head = self._trunk(tokens, positions, segments)
        if isinstance(head, tuple):      # W8A16 head (serve_int8_weights)
            hq, hs = head
            return jnp.einsum("bld,dv->blv", x, hq.astype(self.cfg.dtype),
                              preferred_element_type=jnp.float32) * hs
        if self.cfg.head_int8:
            return _int8_mm(self.cfg.int8_impl)(x, head,
                                                out_dtype=jnp.float32)
        # fp32 logits: the loss softmax wants full precision.
        return jnp.einsum("bld,dv->blv", x, head,
                          preferred_element_type=jnp.float32)

    @nn.compact
    def _trunk(self, tokens: jnp.ndarray,
               positions: Optional[jnp.ndarray] = None,
               segments: Optional[jnp.ndarray] = None):
        cfg = self.cfg
        if cfg.serve_int8_weights:
            if not cfg.decode:
                raise ValueError("serve_int8_weights is a serving (decode) "
                                 "recipe; training keeps bf16 weights")
            if cfg.fused_qkv or cfg.n_experts > 0:
                raise ValueError("serve_int8_weights does not cover "
                                 "fused_qkv or MoE layouts")
        if cfg.use_bias and (cfg.mlp_int8 or cfg.attn_int8
                             or cfg.serve_int8_weights or cfg.fused_qkv):
            raise ValueError("use_bias is not supported with the int8 or "
                             "fused-qkv projection layouts")
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape)
        embed = self.param("embed", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        x = jnp.take(embed, tokens, axis=0)
        if cfg.pos_emb == "learned":
            pos_table = self.param("pos_embed", nn.initializers.normal(0.02),
                                   (cfg.max_seq_len, cfg.d_model),
                                   cfg.param_dtype)
            x = x + jnp.take(pos_table, positions, axis=0)
        x = x.astype(cfg.dtype)

        if cfg.remat and cfg.remat_policy != "mlp":
            # "dots": keep matmul outputs resident, recompute only the cheap
            # elementwise tail — less recompute on the MXU for a modest HBM cost.
            # "dots_kernels" additionally saves Pallas kernel outputs (flash
            # attention o/lse, ~25MB/layer at the headline shape) so backward
            # reuses them instead of re-running the forward kernel (~19ms/step
            # at the 350M bench config).
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            elif cfg.remat_policy == "dots_kernels":
                policy = _dots_and_kernels_saveable
            else:
                policy = None
            block_cls = nn.remat(Block, prevent_cse=False, policy=policy)
        else:
            # remat off, or "mlp" policy (Block handles the inner remat)
            block_cls = Block
        # One traced block body for the whole stack; params stack on axis 0 —
        # compile time is O(1) in depth and rules see a leading "layers" dim.
        stack = nn.scan(
            block_cls,
            variable_axes={"params": 0, "losses": 0, "cache": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=cfg.n_layers,
            unroll=cfg.scan_unroll,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(cfg, name="blocks")
        x, _ = stack(x, positions, segments)

        x = make_norm(cfg, "final_norm")(x)
        if cfg.tie_embeddings:
            # tied head reads the embedding table (also used by the gather)
            # — it stays full-precision under serve_int8_weights
            return x, embed.astype(cfg.dtype).T
        if cfg.serve_int8_weights:
            hq = self.param("lm_head_q", nn.initializers.zeros_init(),
                            (cfg.d_model, cfg.vocab_size), jnp.int8)
            hs = self.param("lm_head_scale", nn.initializers.ones_init(),
                            (cfg.vocab_size,), jnp.float32)
            return x, (hq, hs)
        head = self.param("lm_head", nn.initializers.normal(0.02),
                          (cfg.d_model, cfg.vocab_size), cfg.param_dtype)
        return x, head.astype(cfg.dtype)


def flagship_partition_rules() -> List[PartitionRule]:
    """Megatron-layout rules for scan-stacked params (leading ``layers`` dim).

    fsdp shards the non-contracting weight dim that pairs with the model
    axis's contracting dim, so a layer's forward is: all-gather(fsdp) →
    sharded matmul(model) → reduce-scatter — XLA derives these from the specs.
    """
    return [
        # attention: qkv column-parallel, output row-parallel
        PartitionRule(r"attn/w[qkv]/kernel", P(None, AXIS_FSDP, AXIS_MODEL)),
        PartitionRule(r"attn/wqkv/kernel", P(None, AXIS_FSDP, AXIS_MODEL)),
        PartitionRule(r"attn/wo/kernel", P(None, AXIS_MODEL, AXIS_FSDP)),
        # mlp: gate/up column-parallel, down row-parallel
        PartitionRule(r"mlp/w_(gate|up|gateup)/kernel", P(None, AXIS_FSDP, AXIS_MODEL)),
        PartitionRule(r"mlp/w_down/kernel", P(None, AXIS_MODEL, AXIS_FSDP)),
        # MoE: experts over the expert axis, then megatron within each expert
        PartitionRule(r"moe/router", P(None, AXIS_FSDP, None)),
        PartitionRule(r"moe/w_(gate|up)$", P(None, AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL)),
        PartitionRule(r"moe/w_down$", P(None, AXIS_EXPERT, AXIS_MODEL, AXIS_FSDP)),
        # embeddings: vocab-parallel over model, hidden over fsdp
        PartitionRule(r"(^|/)embed$", P(AXIS_MODEL, AXIS_FSDP)),
        PartitionRule(r"pos_embed", P(None, AXIS_FSDP)),
        PartitionRule(r"lm_head", P(AXIS_FSDP, AXIS_MODEL)),
        # norms and everything else: replicated (default, listed for clarity)
        PartitionRule(r"norm/scale", P()),
    ]


def serving_partition_rules(int8: bool = False) -> List[PartitionRule]:
    """The serving engine's default rule set
    (`tpu_on_k8s/models/serving.py` mesh path): the flagship Megatron
    layout, extended for W8A16 int8 serving trees when ``int8``.

    A quantized kernel splits into ``kernel_q`` (same shape/layout as
    the bf16 kernel — the flagship ``.../kernel`` regexes already match
    it via re.search) and a per-OUT-channel ``kernel_scale`` one dim
    shorter, which the kernel rules would mis-spec (a 3-dim spec on a
    2-dim leaf). The scale rules therefore come FIRST (first match
    wins) and shard each scale exactly like its kernel's output dim:
    ``model`` for column-parallel projections, ``fsdp`` for
    row-parallel ones — so the in-shard rescale of a sharded matmul
    product never needs a gather."""
    rules: List[PartitionRule] = []
    if int8:
        rules += [
            # column-parallel kernels [L, D, F(model)] → scales [L, F]
            PartitionRule(r"attn/w[qkv]/kernel_scale", P(None, AXIS_MODEL)),
            PartitionRule(r"attn/wqkv/kernel_scale", P(None, AXIS_MODEL)),
            PartitionRule(r"mlp/w_(gate|up|gateup)/kernel_scale",
                          P(None, AXIS_MODEL)),
            # row-parallel kernels [L, F(model), D(fsdp)] → scales [L, D]
            PartitionRule(r"attn/wo/kernel_scale", P(None, AXIS_FSDP)),
            PartitionRule(r"mlp/w_down/kernel_scale", P(None, AXIS_FSDP)),
            # vocab-parallel head: lm_head_q [D, V] rides the lm_head
            # rule below; its scale [V] shards with the vocab dim
            PartitionRule(r"lm_head_scale", P(AXIS_MODEL)),
        ]
    return rules + flagship_partition_rules()
