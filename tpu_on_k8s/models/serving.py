"""Continuous batching: requests join and leave a running decode batch.

The reference operator's serving story ends at baking the trained artifact
into an OCI image (`/root/reference/controllers/modelversion` — SURVEY.md
§3.5); the compute plane itself is this framework's own. ``generate()``
(`tpu_on_k8s/models/decode.py`) serves one batch of same-length requests;
real serving traffic is ragged and asynchronous — requests arrive while
others are mid-generation, and a static-batch server pays head-of-line
blocking (the batch runs until its LONGEST member finishes).

TPU-first design — every shape is static so there is exactly ONE compiled
step program for the engine's lifetime:

* The batch dimension is a fixed pool of ``n_slots`` **slots**, each either
  serving one request or free. The cache is one ``[n_slots, max_len, ...]``
  pytree in ``decode_multislot`` mode (`models/transformer.py`): no shared
  cursor; each row appends at its OWN position, and free slots pass the
  out-of-bounds sentinel position so their append drops.
* Admission = one **prefill** program (compiled per 128-bucketed prompt
  length — the same bucketing `decode._bucket_len` uses) run at batch 1
  on the ordinary cursor-mode decode model, then one **admit** program
  that masks the first ``lp`` cache rows into the slot. Prompts pad to the
  bucket; padded positions are masked out of the admitted cache, so a
  handful of prefill programs serve every prompt length.
* The **step** program advances all slots one token — active or not —
  per-row positions select each slot's attention span. Retiring a request
  is a host-side bookkeeping change; the next step simply runs without it
  (its row computes garbage that nobody reads — on TPU that is cheaper
  than a shape change, which would recompile).
* ``step_horizon > 1`` scans that many decode steps inside ONE compiled
  program (`lax.scan`), amortizing the per-step host round-trip — the
  dominant cost when the host↔device link is slow. The trade: admission
  and retirement only happen at horizon boundaries, so a slot that
  finishes mid-horizon wastes the remaining iterations (its surplus
  tokens are discarded host-side; greedy output is unchanged) and a
  queued request waits up to ``horizon`` steps for admission.

The host loop (``step()``) is plain Python: admit from the queue into free
slots, run one device horizon, collect finished requests. One H2D transfer
of two ``[n_slots]`` int vectors per horizon; the cache lives on device.

**Speculative decoding** (``draft_cfg``/``draft_params``): a second, small
model drafts ``spec_k`` greedy tokens per slot per round (one scanned
program over its own slot-pool cache — `_DraftRunner`), and ONE batched
target forward verifies every slot's ``k+1`` chunk; each row emits its
longest agreeing prefix plus the target's correction token — up to
``k+1`` tokens per target forward, token-identical to plain greedy
decode (the oracle `tests/test_speculative.py` pins). Rollback is pure
position bookkeeping: multislot queries attend only ``k_pos <=
position``, so rejected proposals' stale K/V is never attended and the
next round's appends overwrite it. Slots the draft cannot seed (adopted
``KVHandoff``s, imported prefixes) decode plain inside the same
programs; a draft crash (`chaos.SITE_SPEC_DRAFT`) degrades the whole
engine to plain decode — counted, zero silent loss.

**Mesh-sharded serving** (``mesh=``): the engine runs tensor/expert-
parallel over a named ``{data, model, expert}`` mesh
(`parallel/mesh.serving_mesh`) — params shard by `PartitionRule`
(attention heads and MLP/expert dims on ``model``/``expert``,
layernorms replicated; int8 q/scale trees via
`transformer.serving_partition_rules`), the ``[n_slots, max_len, ...]``
KV pool splits kv-heads on ``model`` and slots on ``data``, and every
program — step, spec_verify, prefill (whole, suffix, chunked), admit,
the KV splice — is jitted with explicit shardings (`_ShardPlan`) so the
decode math runs sharded while the host bookkeeping stays position-only.
Speculative decoding composes as the classic big-model shape (replicated
small draft proposing, sharded target verifying); int8 composes via the
scale-aware rules. KV handoffs and prefix exports carry a
`models/layouts.CacheLayout`: gather-on-export, reshard-on-import, so
disagg prefill→decode and fleet prefix reuse work across UNLIKE meshes.
A replica's model-size ceiling is therefore per-chip bytes × chips per
replica, not per-chip bytes alone — the v5e-16 gang serves one big
model once instead of the same small model 16×.
"""
from __future__ import annotations

import dataclasses
import functools
import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from tpu_on_k8s import chaos
from tpu_on_k8s.models.decode import (
    PAGE_TOKENS,
    _bucket_len,
    cache_shapes,
    init_cache,
    quantize_weights_for_serving,
)
from tpu_on_k8s.models.layouts import CacheLayout
from tpu_on_k8s.models.sampling import SamplingParams, sample as _pick
from tpu_on_k8s.models.transformer import Transformer, TransformerConfig
from tpu_on_k8s.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    mesh_axes as _axes_of,
)


class EngineOverloadedError(RuntimeError):
    """``submit()`` refused: in-flight requests (queued + prefilling +
    decoding) already meet ``queue_cap``. The typed rejection for callers
    that bypass the gateway's bounded admission queue
    (`tpu_on_k8s/serve/admission.py`) — an unbounded engine queue would
    otherwise absorb any burst and melt under it (VERDICT r5 weakness #4).
    Carries the saturation snapshot for a 429/Retry-After response."""

    def __init__(self, inflight: int, cap: int) -> None:
        super().__init__(f"engine saturated: {inflight} requests in flight "
                         f">= queue_cap {cap}")
        self.inflight = inflight
        self.cap = cap


class EngineCrashError(RuntimeError):
    """The engine died mid-step: every slot's host/device request state is
    unrecoverable (the in-process shape of a decode-worker process crash).
    ``reset()`` brings the engine itself back — compiled programs and the
    cache pool survive — but in-flight requests are lost; the gateway
    (`tpu_on_k8s/serve/gateway.py`) owns re-admitting them (request
    replay). Raised by chaos injection; an external supervisor translating
    a real worker death should raise it too so recovery stays typed."""


@dataclasses.dataclass
class _Slot:
    request_id: int
    pos: int                      # position of the NEXT append (== tokens
                                  # cached so far)
    last_token: int               # emitted but not yet fed back
    emitted: List[int]
    max_new_tokens: int
    eos_id: Optional[int]
    submitted_at: float = 0.0     # monotonic submit time (metrics)
    on_token: Optional[Any] = None   # streaming callback (rid, token)
    draft: bool = False           # the draft runner holds this slot's
                                  # context KV → the spec rounds may
                                  # propose for it (False: plain decode —
                                  # adopted handoffs, imported prefixes)
    pages: Optional[List[int]] = None   # paged mode: this slot's block
                                  # table (pages[j] backs positions
                                  # [j*page, (j+1)*page)); leading entries
                                  # may ALIAS shared prefix pages —
                                  # refcounts make release uniform


@dataclasses.dataclass
class _Pending:
    request_id: int
    prompt: np.ndarray            # [lp] int32
    max_new_tokens: int
    eos_id: Optional[int]
    submitted_at: float = 0.0
    prefix_id: Optional[int] = None
    on_token: Optional[Any] = None


@dataclasses.dataclass
class _KVPending:
    """A ``submit_kv`` request waiting for a free slot: its prefill
    already happened elsewhere (the handoff carries the KV), so admission
    is a pure cache splice — no prefill program runs here."""

    request_id: int
    handoff: "KVHandoff"
    max_new_tokens: int
    eos_id: Optional[int]
    prefix_id: Optional[int]
    submitted_at: float = 0.0
    on_token: Optional[Any] = None


@dataclasses.dataclass
class _Prefilling:
    """A long prompt mid-chunked-prefill: its KV accumulates in a private
    batch-1 cache, one chunk per engine step, while decode continues for
    everyone else; the reserved slot admits it when the last chunk lands."""

    req: _Pending
    pre_cache: Any                # [1, max_len, ...] accumulating KV
    base: int                     # prefix length (0 without a prefix_id)
    done: int                     # positions cached so far (incl. prefix)
    total: int                    # base + prompt length
    dequeued_at: float
    pages: Optional[List[int]] = None   # paged mode: the block table
                                  # reserved at dequeue (eager — admission
                                  # must not fail after chunks ran)
    fresh_from: int = 0           # leading entries of ``pages`` that
                                  # alias shared prefix pages


def _strip_index(cache: Any) -> Any:
    """Drop the cursor leaves from an ordinary decode cache so its structure
    matches the multislot cache (which has none)."""
    if isinstance(cache, dict):
        return {k: _strip_index(v) for k, v in cache.items() if k != "index"}
    return cache


def _graft_cursorless(template: Any, data: Any) -> Any:
    """Fill a cursor-mode cache ``template``'s KV leaves from a cursorless
    ``data`` pytree (a ``KVHandoff``/exported-prefix payload), keeping the
    template's own ``index`` leaves — the inverse of ``_strip_index``.
    The cursor value is irrelevant: every consumer re-seeds it
    (``_set_cursor``) before use. Payload leaves may be position-trimmed
    (exports carry their bucket, not max_len): the transfer ships the
    trimmed bytes, then zero-pads back out on device — zeros past the
    live positions are never attended."""
    if isinstance(template, dict):
        return {k: (v if k == "index" else _graft_cursorless(v, data[k]))
                for k, v in template.items()}
    leaf = jnp.asarray(np.asarray(data))
    pad = template.shape[2] - leaf.shape[2]
    if pad > 0:
        leaf = jnp.pad(leaf, [(0, 0), (0, 0), (0, pad)]
                       + [(0, 0)] * (leaf.ndim - 3))
    return leaf


def _host_leaves(cache: Any) -> Any:
    """Device → host copy of a cursorless cache pytree (numpy leaves)."""
    return jax.tree.map(np.asarray, cache)


def _cache_nbytes(cache: Any) -> int:
    return sum(int(leaf.nbytes) for leaf in jax.tree.leaves(cache))


def _cache_checksum(cache: Any, *meta) -> str:
    """Stable content hash of a host cache pytree plus metadata ints —
    what lets a decode replica REJECT a handoff corrupted in transfer
    instead of decoding silently-wrong tokens from a poisoned cache.
    Leaf order is ``jax.tree`` flatten order: deterministic for a fixed
    tree structure."""
    import hashlib
    h = hashlib.blake2b(digest_size=16)
    h.update(repr(tuple(meta)).encode())
    for leaf in jax.tree.leaves(cache):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


class _ShardPlan:
    """The engine's explicit sharding layout over a named serving mesh
    (`tpu_on_k8s/parallel/mesh.serving_mesh`): params by partition rule
    (attention heads and MLP/expert dims on ``model``/``expert``,
    layernorms replicated — validated for divisibility at construction,
    so a bad rule is a typed ``ShardingValidationError`` naming the
    param path, dim, and axis instead of an XLA error deep in compile),
    the ``[n_slots, max_len, ...]`` KV pool with its kv-head dim on
    ``model`` and the slot dim on ``data``, per-request prefill caches
    kv-head-sharded only (batch 1 cannot split on ``data``), and every
    per-slot token/position vector replicated — the bookkeeping stays
    position-only while the decode math runs tensor-parallel. Every
    engine program is jitted against these shardings explicitly; XLA's
    SPMD partitioner inserts the collectives."""

    def __init__(self, mesh, params, rules, n_slots: int) -> None:
        from tpu_on_k8s.parallel.partition import named_sharding
        self.mesh = mesh
        self.axes = _axes_of(mesh)
        self.n_chips = int(mesh.devices.size)
        self.n_model = int(mesh.shape.get(AXIS_MODEL, 1))
        self.n_data = int(mesh.shape.get(AXIS_DATA, 1))
        self.n_slots = n_slots
        self.replicated = NamedSharding(mesh, PartitionSpec())
        # validates every (rule, param dim, axis size) triple up front
        self.params = named_sharding(params, mesh, rules)

    def kv_sharding(self, shape, *, slots_on_data: bool) -> NamedSharding:
        """Sharding for one cache leaf: k/v ``[L, S, max_len, Hkv, Dh]``
        and cache-int8 scales ``[L, S, max_len, Hkv]`` split their
        kv-head dim over ``model`` (each chip holds only its heads'
        cache bytes) and — for the slot pool — the slot dim over
        ``data`` when it divides; cursor/index leaves and non-dividing
        dims replicate."""
        spec = [None] * len(shape)
        if len(shape) >= 4 and shape[3] % self.n_model == 0:
            spec[3] = AXIS_MODEL
        if (slots_on_data and self.n_data > 1 and len(shape) >= 2
                and shape[1] % self.n_data == 0):
            spec[1] = AXIS_DATA
        while spec and spec[-1] is None:   # canonical short form
            spec.pop()
        if not spec:
            return self.replicated
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def cache_shardings(self, tree, *, slots_on_data: bool = False):
        """Sharding pytree for a cache (arrays or ShapeDtypeStructs)."""
        return jax.tree.map(
            lambda leaf: self.kv_sharding(tuple(leaf.shape),
                                          slots_on_data=slots_on_data),
            tree)

    def put_params(self, params):
        from tpu_on_k8s.parallel.mesh import put_global
        return jax.tree.map(put_global, params, self.params)

    def put_cache(self, tree, *, slots_on_data: bool = False):
        """Lay a host/device cache pytree out under this plan — the
        reshard-on-import half of the cross-mesh KV contract (the
        export half gathers to host numpy, so any source mesh lands
        here identically)."""
        return jax.tree.map(
            lambda leaf: jax.device_put(
                leaf, self.kv_sharding(tuple(leaf.shape),
                                       slots_on_data=slots_on_data)),
            tree)

    def bytes_per_chip(self, tree) -> int:
        """Per-chip bytes of a sharded pytree (each leaf's shard shape
        times its itemsize) — the number the serve_load ``--shard`` arm
        charts shrinking with the ``model`` axis."""
        total = 0
        for leaf in jax.tree.leaves(tree):
            shape = tuple(leaf.shape)
            shard = (leaf.sharding.shard_shape(shape)
                     if isinstance(leaf, jax.Array) else shape)
            n = 1
            for d in shard:
                n *= int(d)
            total += n * leaf.dtype.itemsize
        return total


class _LruPrograms:
    """A bounded compiled-program cache: the per-bucket prefill / suffix /
    admit-range (and paged gather/admit) programs key on shapes drawn from
    request traffic, so an adversarial long tail of prompt lengths could
    otherwise grow compile state without bound. LRU keyed on the shape
    tuple; every miss fires ``on_compile`` (the ``programs_compiled``
    counter on `metrics.PagedKVMetrics`) so retrace pressure is visible
    on a dashboard, not discovered as creeping host RSS. Dropping a
    program costs only a retrace on next use — never correctness."""

    def __init__(self, cap: int = 32,
                 on_compile: Optional[Callable[[], None]] = None) -> None:
        if cap < 1:
            raise ValueError(f"program cache cap must be >= 1, got {cap}")
        self._cap = cap
        self._on_compile = on_compile
        self._d: "OrderedDict[Any, Any]" = OrderedDict()

    def get(self, key, build):
        fn = self._d.get(key)
        if fn is not None:
            self._d.move_to_end(key)
            return fn
        fn = build()
        if self._on_compile is not None:
            self._on_compile()
        self._d[key] = fn
        while len(self._d) > self._cap:
            self._d.popitem(last=False)
        return fn

    def __len__(self) -> int:
        return len(self._d)

    def __iter__(self):
        """Cached keys, LRU→MRU (tests introspect what compiled)."""
        return iter(self._d)

    def __contains__(self, key) -> bool:
        return key in self._d


class _PagePool:
    """Host-side allocator for the paged KV pool: fixed-size pages of
    ``page`` token positions, refcounted so shared-prefix pages can be
    aliased into many slots' block tables (copy-on-write happens at the
    block-table level — a fork writes its OWN fork/suffix pages and only
    REFERENCES the shared full-prefix pages, so a write past the fork can
    never touch a sibling's bytes).

    Page id 0 is the reserved null page: permanently zero on device, it
    backs every unallocated block-table entry, so overshoot appends
    (horizon/speculative writes past a request's reservation) land there
    and are wiped after every program that could dirty it. Real pages are
    handed out ascending from a LIFO free stack — fully deterministic, so
    seeded replays see identical page placement.

    Lock order: callers hold the engine lock first when they hold both;
    this lock is a leaf (the pool calls nothing that locks)."""

    def __init__(self, n_pages: int) -> None:
        if n_pages < 1:
            raise ValueError(f"kv_pages must be >= 1, got {n_pages}")
        self.capacity = n_pages
        # pop() yields 1, 2, 3, ... — ascending first-use order
        self._free: List[int] = list(range(n_pages, 0, -1))
        self._refs = np.zeros(n_pages + 1, np.int32)
        self._lock = threading.Lock()

    def available(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self.capacity - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages at refcount 1, or None (all-or-nothing) when
        the pool cannot supply them — the caller stalls admission."""
        if n == 0:
            return []
        with self._lock:
            if len(self._free) < n:
                return None
            pids = [self._free.pop() for _ in range(n)]
            for p in pids:
                self._refs[p] = 1
            return pids

    def retain(self, pids: List[int]) -> None:
        """Alias already-live pages into another block table."""
        with self._lock:
            for p in pids:
                if self._refs[p] < 1:
                    raise ValueError(f"retain of dead page {p}")
                self._refs[p] += 1

    def release(self, pids: List[int]) -> int:
        """Drop one reference per pid; pages reaching zero return to the
        free stack (in the given order). Returns the count freed."""
        freed = 0
        with self._lock:
            for p in pids:
                if self._refs[p] < 1:
                    raise ValueError(f"release of dead page {p}")
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    self._free.append(p)
                    freed += 1
        return freed


@dataclasses.dataclass
class KVHandoff:
    """A completed prefill's KV, host-resident and engine-portable — the
    payload the disaggregated fleet moves from its prefill pool to its
    decode pool (`tpu_on_k8s/serve/disagg.py`).

    ``cache`` is a cursorless batch-1 pytree (numpy leaves, the prefill
    model's stripped structure — exactly what ``_admit`` masks into a
    slot), position-trimmed to the 128-bucket of ``pos`` so payload,
    copy, and checksum bytes scale with the request rather than the
    engine's max_len. ``pos`` counts TOTAL cached positions; ``base`` counts
    leading positions NOT carried (a suffix-only handoff: the shared
    prefix identified by ``prefix_hash`` is expected resident on the
    adopting engine, so only the suffix's KV crosses the wire —
    position-absolute RoPE makes the spliced rows exact). ``emitted``
    holds the tokens already produced (≥ 1: the prefill's first token),
    so an adopted request resumes mid-stream with its budget intact.
    ``verify()`` recomputes the transfer checksum — a corrupted payload
    must be rejected, never decoded.

    ``layout`` (`models/layouts.CacheLayout`) records the SOURCE
    engine's mesh and the device→host gather bytes the export paid:
    every export is gathered to the full logical array and every import
    reshards under the adopting engine's own mesh, so a handoff crosses
    UNLIKE meshes (sharded prefill → differently-sharded decode, or
    either way to a single-program engine) without either side knowing
    the other's shape. The layout is metadata, not payload — it stays
    outside the checksum, which covers exactly the transferred KV
    bytes."""

    cache: Any
    pos: int
    first_token: int
    emitted: Tuple[int, ...]
    base: int = 0
    prefix_hash: Optional[str] = None
    checksum: str = ""
    layout: Optional[CacheLayout] = None

    def seal(self) -> "KVHandoff":
        self.checksum = _cache_checksum(self.cache, self.pos, self.base,
                                        self.emitted)
        return self

    def verify(self) -> bool:
        return self.checksum == _cache_checksum(self.cache, self.pos,
                                                self.base, self.emitted)

    @property
    def nbytes(self) -> int:
        return _cache_nbytes(self.cache)


class _DraftRunner:
    """The draft half of batched speculative decoding: a second (small)
    model kept position-synchronized with the engine's slot pool.

    The draft owns its OWN ``[n_slots, max_len, ...]`` multislot cache.
    Every admission seeds the admitted slot's draft row with a draft
    prefill of the request's context (prefix + prompt — one cheap
    prefill; the draft never chunks), and each spec round scans ``k+1``
    greedy draft steps over ALL slots in one compiled program
    (``propose``). Rows the draft cannot seed (adopted ``KVHandoff``s —
    no prompt tokens travel with a handoff — or ``import_prefix`` ids
    the draft never saw) ride the rounds at the out-of-bounds sentinel
    position: their appends drop and their proposals are ignored, so one
    program serves a mixed pool.

    Rollback is free in multislot mode: a query attends only
    ``k_pos <= position`` and every append lands at the position of the
    token being fed — so rejected proposals' stale K/V (in BOTH caches)
    is never attended and is overwritten by the next round's appends
    before any query could reach it. No cursor rebuild, no host-side
    cache surgery — exactly the invariant slot retirement already
    relies on.

    Greedy only (argmax): token identity with plain decode is the
    correctness contract, and sampled speculation needs rejection
    sampling this engine does not implement.

    **Mesh composition** (the classic big-model serving shape): on a
    mesh-sharded engine the draft REPLICATES — its params and slot-pool
    cache are device_put replicated and its programs jit with explicit
    replicated in/out shardings, so every chip runs the whole small
    draft locally (zero collectives) while the sharded target's one
    batched verify runs tensor-parallel. A draft small enough to be
    worth speculating with is small enough to replicate."""

    def __init__(self, cfg: TransformerConfig, params, n_slots: int,
                 max_len: int, k: int, mesh=None,
                 on_compile: Optional[Callable[[], None]] = None) -> None:
        if cfg.pos_emb == "rope":
            cfg = dataclasses.replace(cfg, max_seq_len=max_len)
        elif cfg.max_seq_len < max_len:
            # learned positional tables cannot reach the engine's length
            raise ValueError(
                f"draft max_seq_len {cfg.max_seq_len} < engine max_len "
                f"{max_len} (learned positions cannot extrapolate)")
        base = dataclasses.replace(cfg, decode=True, remat=False,
                                   attn_impl="xla")
        self.cfg = base
        self._rep = (NamedSharding(mesh, PartitionSpec())
                     if mesh is not None else None)
        if self._rep is not None:
            params = jax.device_put(params, self._rep)
        self.params = params
        self.k = k
        self.max_len = max_len
        self._step_model = Transformer(
            dataclasses.replace(base, decode_multislot=True))
        self._prefill_model = Transformer(base)
        self.cache = init_cache(self._step_model, n_slots)
        if self._rep is not None:
            self.cache = jax.device_put(self.cache, self._rep)
        self.prefixes: Dict[int, Tuple[Any, int]] = {}   # engine pid → KV
        self._prefill_progs = _LruPrograms(32, on_compile)
        self._suffix_progs = _LruPrograms(32, on_compile)
        model = self._step_model

        @functools.partial(
            jax.jit, donate_argnums=(1,),
            out_shardings=((self._rep, self._rep)
                           if self._rep is not None else None))
        def propose(params, cache, toks, pos):
            """``k+1`` scanned greedy draft steps; returns the cache and
            the first k proposals [k, n_slots] (the k+1-th feed exists
            only to cache d_k so a fully-accepted round's next draft
            appends right after it — same shape as the batch-1
            ``draft_k`` program in `models/decode.py`)."""
            def body(carry, _):
                cache, tok, p = carry
                logits, upd = model.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    p[:, None], mutable=["cache"])
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (upd["cache"], nxt, p + 1), nxt

            (cache, _, _), toks_out = jax.lax.scan(
                body, (cache, toks, pos), None, length=self.k + 1)
            return cache, toks_out[:self.k]

        @functools.partial(
            jax.jit, donate_argnums=(0,),
            out_shardings=self._rep if self._rep is not None else None)
        def admit(cache, pre_cache, slot, lp, row):
            """Identical write to the engine's admit program, over the
            draft's cache shapes."""
            def write(shared, pre):
                keep = jnp.arange(shared.shape[2]) < lp
                keep = keep.reshape((1, -1) + (1,) * (pre.ndim - 3))
                return shared.at[:, slot].set(
                    jnp.where(keep, pre[:, row], shared[:, slot]))
            return jax.tree.map(write, cache, _strip_index(pre_cache))

        self._propose_fn = propose
        self._admit = admit

    def propose(self, toks: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """One draft phase over the whole slot pool → proposals
        [k, n_slots] (host). Rows at the sentinel position produce
        garbage the caller ignores."""
        self.cache, out = self._propose_fn(
            self.params, self.cache, jnp.asarray(toks), jnp.asarray(pos))
        return np.asarray(out)

    def _prefill_fn(self, bucket: int):
        def build():
            model = self._prefill_model
            shapes = cache_shapes(model, 1)

            @functools.partial(
                jax.jit,
                out_shardings=self._rep if self._rep is not None else None)
            def prefill(params, prompt):
                cache = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), shapes)
                positions = jnp.arange(bucket, dtype=jnp.int32)[None, :]
                _, upd = model.apply(
                    {"params": params, "cache": cache}, prompt, positions,
                    mutable=["cache"])
                return upd["cache"]

            return prefill

        return self._prefill_progs.get(bucket, build)

    def _suffix_fn(self, bucket: int):
        def build():
            from tpu_on_k8s.models.decode import _set_cursor
            model = self._prefill_model

            @functools.partial(
                jax.jit,
                out_shardings=self._rep if self._rep is not None else None)
            def prefill(params, pre_cache, suffix, plen):
                cache = _set_cursor(pre_cache, plen)
                positions = plen + jnp.arange(bucket,
                                              dtype=jnp.int32)[None, :]
                _, upd = model.apply(
                    {"params": params, "cache": cache}, suffix, positions,
                    mutable=["cache"])
                return upd["cache"]

            return prefill

        return self._suffix_progs.get(bucket, build)

    def register_prefix(self, pid: int, tokens: np.ndarray) -> None:
        """Draft-prefill a shared prefix under the ENGINE's prefix id, so
        prefix-seeded admissions can seed their draft rows too."""
        lp = int(tokens.size)
        bucket = _bucket_len(lp, self.cfg.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :lp] = tokens
        cache = self._prefill_fn(bucket)(self.params, jnp.asarray(padded))
        self.prefixes[pid] = (cache, lp)

    def drop_prefix(self, pid: int) -> None:
        self.prefixes.pop(pid, None)

    def seed(self, slot: int, prompt: np.ndarray,
             prefix_id: Optional[int]) -> bool:
        """Prefill ``prompt`` (the suffix, with ``prefix_id``) through the
        draft and splice it into the draft cache's row ``slot``. False
        when the row cannot be drafted — an ``import_prefix`` id the
        draft never saw prefilled; the slot then decodes plain."""
        if prefix_id is not None:
            entry = self.prefixes.get(prefix_id)
            if entry is None:
                return False
            pre, plen = entry
            slen = int(prompt.size)
            bucket = _bucket_len(slen, self.cfg.max_seq_len - plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :slen] = prompt
            cache = self._suffix_fn(bucket)(
                self.params, pre, jnp.asarray(padded), jnp.int32(plen))
            lp = plen + slen
        else:
            lp = int(prompt.size)
            bucket = _bucket_len(lp, self.cfg.max_seq_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :lp] = prompt
            cache = self._prefill_fn(bucket)(self.params,
                                             jnp.asarray(padded))
        self.cache = self._admit(self.cache, cache, jnp.int32(slot),
                                 jnp.int32(lp), jnp.int32(0))
        return True


class ContinuousBatchingEngine:
    """Slot-pool continuous batching over one model + parameter set.

    ``submit()`` enqueues a request; ``step()`` advances the world by one
    decode step (admitting queued requests into free slots first) and
    returns the requests that finished on that step; ``run()`` drains
    everything. Greedy by default; ``temperature > 0`` samples.
    """

    def __init__(self, cfg: TransformerConfig, params, n_slots: int = 8,
                 max_len: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 rng: Optional[jax.Array] = None, mesh=None, rules=None,
                 step_horizon: int = 1, metrics=None,
                 int8_weights: bool = False, prefill_chunk: int = 0,
                 queue_cap: Optional[int] = None, on_retire=None,
                 clock=time.monotonic,
                 draft_cfg: Optional[TransformerConfig] = None,
                 draft_params=None, spec_k: int = 4, spec_metrics=None,
                 on_spec_round=None, shard_metrics=None,
                 kv_pages: int = 0, page_tokens: Optional[int] = None,
                 kv_metrics=None):
        if step_horizon < 1:
            raise ValueError(f"step_horizon must be >= 1, got {step_horizon}")
        if queue_cap is not None and queue_cap < 1:
            raise ValueError(f"queue_cap must be >= 1, got {queue_cap}")
        if prefill_chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, got "
                             f"{prefill_chunk}")
        if int8_weights:
            cfg = dataclasses.replace(cfg, serve_int8_weights=True)
            params = quantize_weights_for_serving(params)
        #: Optional ``tpu_on_k8s.metrics.metrics.ServingMetrics`` — request
        #: counters, TTFT/queue-wait/latency histograms, slot/queue gauges,
        #: scrapeable via the same metrics.serve() path the operator uses.
        self.metrics = metrics
        #: every queue/slot timestamp (submitted_at, dequeued_at, the
        #: TTFT/queue-wait/latency observations) reads THIS clock — the
        #: gateway/fleet/serve_load thread their injectable (virtual)
        #: clock through, so seeded replays are wall-time-free end to end
        self._clock = clock
        max_len = max_len or cfg.max_seq_len
        if max_len > cfg.max_seq_len and cfg.pos_emb != "rope":
            raise ValueError("max_len beyond the trained table needs rope")
        if cfg.pos_emb != "rope":
            # learned positional tables are sized by max_seq_len; shrinking
            # it would reshape the param, so serve at the trained length
            max_len = cfg.max_seq_len
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        # ---- paged KV pool configuration ------------------------------
        #: optional ``metrics.PagedKVMetrics`` — page occupancy gauges,
        #: alloc/alias/stall counters, the programs_compiled counter (the
        #: LRU program caches count compiles in BOTH modes)
        self.kv_metrics = kv_metrics
        if kv_pages < 0:
            raise ValueError(f"kv_pages must be >= 0, got {kv_pages}")
        #: tokens per KV page. Defaults to the position-bucket granule
        #: (`decode.PAGE_TOKENS`) so pages and buckets coincide by
        #: construction; tiny configs (max_len < PAGE_TOKENS) shrink it
        #: to max_len, and an explicit override must keep the alignment:
        #: every bucket a request can export is a PAGE_TOKENS multiple or
        #: max_len itself, so the page must divide both.
        page = (page_tokens if page_tokens is not None
                else min(PAGE_TOKENS, max_len))
        if kv_pages:
            if page < 1 or max_len % page != 0:
                raise ValueError(f"page_tokens {page} must divide max_len "
                                 f"{max_len}")
            if max_len > PAGE_TOKENS and PAGE_TOKENS % page != 0:
                raise ValueError(
                    f"page_tokens {page} must divide the position bucket "
                    f"granule {PAGE_TOKENS} (exports trim to bucket "
                    f"multiples; a non-dividing page would misalign them)")
            if step_horizon > page:
                raise ValueError(
                    f"step_horizon {step_horizon} exceeds page_tokens "
                    f"{page}: a horizon's appends must span at most two "
                    f"pages (the scatter-back window)")
        self.page_tokens = page
        self._nb_total = max_len // page if kv_pages else 0
        #: True on paged engines: ``import_prefix`` accepts
        #: ``base_pid``/``base_len`` and aliases the ancestor's full
        #: pages instead of copying — the prefix store gates its
        #: reference-moving promote path on this
        self.supports_page_alias = bool(kv_pages)
        #: > 0: prompts longer than this prefill one chunk per engine step
        #: (in a private cache; the slot admits when the last chunk lands)
        #: instead of one long synchronous prefill — decode for the OTHER
        #: slots continues between chunks, bounding the TTFT spike a long
        #: prompt inflicts on everyone ("chunked prefill"). 0 = whole-prompt
        #: admission. Chunks pad to PAGE_TOKENS prefill buckets, so at
        #: production lengths the chunk rounds UP to a bucket multiple — a
        #: smaller chunk would pay the full bucket's FLOPs anyway.
        if prefill_chunk and max_len > PAGE_TOKENS:
            prefill_chunk = -(-prefill_chunk // PAGE_TOKENS) * PAGE_TOKENS
        self.prefill_chunk = prefill_chunk
        self.sampling = SamplingParams(temperature=temperature,
                                       top_k=top_k, top_p=top_p)
        self._rng = rng if rng is not None else jax.random.key(0)

        base = dataclasses.replace(cfg, decode=True, remat=False,
                                   attn_impl="xla", max_seq_len=max_len)
        self._step_model = Transformer(
            dataclasses.replace(base, decode_multislot=True))
        self._prefill_model = Transformer(base)

        cache_shardings = token_shardings = pool_shardings = None
        plan: Optional[_ShardPlan] = None
        if mesh is not None:
            # Tensor-parallel / expert-parallel serving: params shard by
            # the serving partition rules (Megatron layout, int8 q/scale
            # aware — per-layer all-gather/reduce-scatter over the
            # `model` axis ride ICI, MoE expert tables split on
            # `expert`), the KV pool shards kv-heads on `model` and
            # slots (dense) or pages (paged) on `data`, and the per-slot
            # token/position vectors replicate. Same compiled programs,
            # just sharded — XLA inserts the collectives; `_ShardPlan`
            # holds every layout.
            if rules is None:
                from tpu_on_k8s.models.transformer import (
                    serving_partition_rules,
                )
                rules = serving_partition_rules(
                    int8=cfg.serve_int8_weights)
            plan = _ShardPlan(mesh, params, rules, n_slots)
            params = plan.put_params(params)
            token_shardings = plan.replicated
        self._cache = None
        self._pool: Optional[_PagePool] = None
        self._pool_cache = None
        if kv_pages:
            # The paged pool: every KV leaf becomes [L, P, page, ...] —
            # page axis where the dense pool had slots, position axis cut
            # to one page. P = kv_pages + 1: page id 0 is the null page
            # (permanently zero; unallocated block-table entries point at
            # it so overshoot appends drop). The pool shards exactly like
            # the dense pool — kv-heads on `model` (dim 3 is unchanged),
            # pages on `data` where slots used to be (dim 1, padded up so
            # the axis divides) — via the same `_ShardPlan` machinery.
            total = kv_pages + 1
            if plan is not None and plan.n_data > 1:
                total = -(-total // plan.n_data) * plan.n_data
            self._pool = _PagePool(total - 1)
            shapes = cache_shapes(self._step_model, n_slots)
            pool_struct = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(
                    (s.shape[0], total, page) + tuple(s.shape[3:]),
                    s.dtype),
                shapes)
            if plan is not None:
                pool_shardings = plan.cache_shardings(pool_struct,
                                                      slots_on_data=True)
                self._pool_cache = jax.tree.map(
                    lambda s, sh: jax.device_put(
                        jnp.zeros(s.shape, s.dtype), sh),
                    pool_struct, pool_shardings)
            else:
                self._pool_cache = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), pool_struct)
            #: per-slot block tables, host-resident (int32 page ids; 0 =
            #: unallocated → null page). One small H2D per program call.
            self._tables = np.zeros((n_slots, self._nb_total), np.int32)
            self._prefix_pages: Dict[int, List[int]] = {}
            if kv_metrics is not None:
                kv_metrics.set_gauge("pages_total", self._pool.capacity)
                kv_metrics.set_gauge("pages_in_use", 0)
        else:
            self._cache = init_cache(self._step_model, n_slots)
            if plan is not None:
                cache_shardings = plan.cache_shardings(self._cache,
                                                       slots_on_data=True)
                self._cache = jax.tree.map(jax.device_put, self._cache,
                                           cache_shardings)
        self.mesh = mesh
        self._plan = plan
        #: {axis: size} of the mesh's non-trivial axes ({} = single
        #: program) — the replica's sharding signature (identity checks,
        #: ShardMetrics gauges, the layout block exports carry)
        self.mesh_axes = plan.axes if plan is not None else {}
        self.n_chips = plan.n_chips if plan is not None else 1
        self._params = params
        #: optional ``metrics.ShardMetrics`` — mesh-shape gauges,
        #: per-chip param/KV byte gauges, export-gather accounting
        self.shard_metrics = shard_metrics
        if shard_metrics is not None:
            shard_metrics.set_mesh_axes(self.mesh_axes)
            shard_metrics.set_gauge("param_bytes_per_chip",
                                    self.param_bytes_per_chip)
            shard_metrics.set_gauge("kv_bytes_per_chip",
                                    self.kv_bytes_per_chip)

        sp = self.sampling
        self.step_horizon = horizon = step_horizon
        # explicit in/out shardings for every program: decode math runs
        # tensor-parallel (params/cache sharded) while the bookkeeping
        # stays position-only (token/position vectors replicated)
        _rep = token_shardings
        step_in = ((plan.params, cache_shardings, _rep, _rep, _rep)
                   if plan is not None else None)

        @functools.partial(
            jax.jit, donate_argnums=(1,),
            in_shardings=step_in,
            out_shardings=((cache_shardings, token_shardings)
                           if mesh is not None else None))
        def step(params, cache, toks, pos, key):
            """``horizon`` decode steps in one program; returns the cache
            and the [horizon, n_slots] token stack (retired rows' surplus
            is discarded by the host)."""
            def body(carry, step_key):
                cache, tok, p = carry
                logits, upd = self._step_model.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    p[:, None], mutable=["cache"])
                nxt = _pick(logits[:, -1], step_key, sp)
                return (upd["cache"], nxt, p + 1), nxt

            (cache, _, _), toks_out = jax.lax.scan(
                body, (cache, toks, pos), jax.random.split(key, horizon))
            return cache, toks_out

        # ---- paged-mode programs ----------------------------------------
        # The paged step gathers each slot's block table into the SAME
        # [L, S, max_len, ...] view the dense step decodes over and runs
        # the IDENTICAL model apply — token identity with dense mode is
        # by construction, not by re-derivation (unallocated blocks read
        # the null page's zeros; positions past a slot's cursor are never
        # attended either way). Afterwards only each slot's two TAIL
        # pages (the at-most-two pages a horizon's appends can touch —
        # validated horizon <= page) scatter back to the pool; every
        # other gathered page is either unchanged private data or a
        # shared prefix page that appends can never reach (appends land
        # at pos >= the fork, and aliased pages all lie below it). The
        # RESIDENT allocation is the pool — proportional to live tokens;
        # the gathered view is a transient working set inside the step
        # (a paged-attention kernel indexing pages in place is the
        # follow-up optimization, not a correctness requirement).
        self._pool_shardings = pool_shardings
        self._gather_view = self._scatter_tails = None
        if self._pool is not None:
            nb = self._nb_total

            def _gather_view(pool, tables):
                def g(pl):
                    x = pl[:, tables]          # [L, S, nb, page, *rest]
                    return x.reshape((x.shape[0], tables.shape[0],
                                      nb * page) + x.shape[4:])
                return jax.tree.map(g, pool)

            def _scatter_tails(pool, cache, tail_blocks, tail_pids):
                # tail_blocks [S, 2] block indices, tail_pids [S, 2] their
                # page ids (0 = free slot / unallocated → the write lands
                # in the null page, which is re-zeroed last). Duplicate
                # ids only ever carry identical bytes (b0 == b1) or hit
                # the re-zeroed null page, so the scatter is
                # order-insensitive and replays byte-identically.
                def s(pl, cl):
                    u = cl.reshape((cl.shape[0], cl.shape[1], nb, page)
                                   + cl.shape[3:])
                    idx = tail_blocks.reshape(
                        (1,) + tail_blocks.shape + (1,) * (u.ndim - 3))
                    tails = jnp.take_along_axis(u, idx, axis=2)
                    tails = tails.reshape((tails.shape[0], -1, page)
                                          + tails.shape[4:])
                    pl = pl.at[:, tail_pids.reshape(-1)].set(tails)
                    return pl.at[:, 0].set(jnp.zeros_like(pl[:, 0]))
                return jax.tree.map(s, pool, cache)

            self._gather_view = _gather_view
            self._scatter_tails = _scatter_tails
            paged_in = ((plan.params, pool_shardings, _rep, _rep, _rep,
                         _rep, _rep, _rep) if plan is not None else None)

            @functools.partial(
                jax.jit, donate_argnums=(1,),
                in_shardings=paged_in,
                out_shardings=((pool_shardings, token_shardings)
                               if plan is not None else None))
            def step_paged(params, pool, tables, toks, pos,
                           tail_blocks, tail_pids, key):
                """The dense ``step`` over a gathered page view; returns
                the pool (tail pages scattered back) and the same
                [horizon, n_slots] token stack."""
                cache = _gather_view(pool, tables)

                def body(carry, step_key):
                    cache, tok, p = carry
                    logits, upd = self._step_model.apply(
                        {"params": params, "cache": cache}, tok[:, None],
                        p[:, None], mutable=["cache"])
                    nxt = _pick(logits[:, -1], step_key, sp)
                    return (upd["cache"], nxt, p + 1), nxt

                (cache, _, _), toks_out = jax.lax.scan(
                    body, (cache, toks, pos), jax.random.split(key, horizon))
                return _scatter_tails(pool, cache, tail_blocks,
                                      tail_pids), toks_out

            self._step_paged = step_paged

        @functools.partial(
            jax.jit, donate_argnums=(0,),
            out_shardings=cache_shardings if mesh is not None else None)
        def admit(cache, pre_cache, slot, lp, row):
            """Mask row ``row`` of a prefill cache's first ``lp`` positions
            into row ``slot`` of the pool (batched prefills admit one row
            per call). Positions >= lp (pad garbage) keep the slot's old
            bytes — never attended, same invariant as appends."""
            def write(shared, pre):
                # cache leaves are layer-stacked by the block scan
                # (variable_axes {"cache": 0}): [L, B, max_len, ...]
                keep = jnp.arange(shared.shape[2]) < lp        # positions
                keep = keep.reshape((1, -1) + (1,) * (pre.ndim - 3))
                return shared.at[:, slot].set(
                    jnp.where(keep, pre[:, row], shared[:, slot]))
            return jax.tree.map(write, cache, _strip_index(pre_cache))

        admit_range_progs = _LruPrograms(32, self._count_compile)

        def admit_range_for(pb: int):
            """``admit_range`` program for a pre cache whose position
            axis is trimmed to ``pb`` (export/handoff payloads carry the
            PAGE_TOKENS-multiple bucket of their live positions, not
            max_len — the transfer and checksum scale with the request):
            mask positions ``[lo, hi)`` of a CURSORLESS batch cache's row
            ``row`` into slot ``slot`` (``lo=0`` for a full handoff;
            ``lo=base`` to lay a suffix over locally-seeded prefix
            rows), zero-padding the pre rows back to max_len on device
            first. Positions outside the range keep the slot's bytes,
            same never-attended invariant as ``admit``. One program per
            position bucket — LRU-bounded like every per-bucket program
            cache (``pb == max_len`` is the untrimmed case)."""
            def build():
                @functools.partial(
                    jax.jit, donate_argnums=(0,),
                    out_shardings=(cache_shardings
                                   if mesh is not None else None))
                def admit_range(cache, pre_cache, slot, lo, hi, row):
                    def write(shared, pre):
                        pad = shared.shape[2] - pre.shape[2]
                        pre = jnp.pad(
                            pre, [(0, 0), (0, 0), (0, pad)]
                            + [(0, 0)] * (pre.ndim - 3))
                        span = jnp.arange(shared.shape[2])
                        keep = (span >= lo) & (span < hi)
                        keep = keep.reshape(
                            (1, -1) + (1,) * (pre.ndim - 3))
                        return shared.at[:, slot].set(
                            jnp.where(keep, pre[:, row], shared[:, slot]))
                    return jax.tree.map(write, cache, pre_cache)
                return admit_range
            return admit_range_progs.get(pb, build)

        self._step = step
        self._admit = admit
        self._admit_range_for = admit_range_for
        self._prefill_cache = _LruPrograms(32, self._count_compile)
        self._suffix_prefill_cache = _LruPrograms(32, self._count_compile)
        self._paged_admit_progs = _LruPrograms(16, self._count_compile)
        self._paged_gather_progs = _LruPrograms(16, self._count_compile)
        self._prefixes: Dict[int, Any] = {}   # id → (cache pytree, length)
        self._next_prefix_id = 0

        # ---- speculative decoding (batched drafts over the slot pool) ----
        #: optional ``metrics.SpecMetrics`` — proposed/accepted counters,
        #: the acceptance-rate gauge, rollback + draft-crash counters
        self.spec_metrics = spec_metrics
        #: ``on_spec_round(request_ids, draft_s, verify_s, proposed,
        #: accepted)`` fires after each spec round (outside the lock) —
        #: the gateway turns it into ``spec.draft``/``spec.verify`` span
        #: events on the live requests' decode spans so `trace_report`
        #: can attribute draft overhead. Like ``on_retire``, a raising
        #: callback detaches with a warning.
        self._on_spec_round = on_spec_round
        self._spec_k = spec_k
        self._draft: Optional[_DraftRunner] = None
        if draft_cfg is not None or draft_params is not None:
            if draft_cfg is None or draft_params is None:
                raise ValueError("draft_cfg and draft_params come together")
            if step_horizon != 1:
                raise ValueError(
                    "speculative decoding replaces the step horizon "
                    "(each round already scans k draft steps); use "
                    "step_horizon=1")
            if not self.sampling.is_greedy:
                raise ValueError(
                    "speculative decoding is greedy-only: token identity "
                    "with plain decode is the correctness contract, and "
                    "sampled acceptance needs rejection sampling")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            if self._pool is not None and spec_k + 1 > page:
                raise ValueError(
                    f"spec_k + 1 ({spec_k + 1}) exceeds page_tokens "
                    f"{page}: a verify chunk's appends must span at most "
                    f"two pages (the scatter-back window)")
            # on a mesh the draft replicates (every chip runs the whole
            # small model) while the sharded target verifies
            # tensor-parallel — the classic big-model serving shape
            self._draft = _DraftRunner(draft_cfg, draft_params, n_slots,
                                       max_len, spec_k, mesh=mesh,
                                       on_compile=self._count_compile)

            @functools.partial(
                jax.jit, donate_argnums=(1,),
                in_shardings=((plan.params, cache_shardings, _rep, _rep)
                              if plan is not None else None),
                out_shardings=((cache_shardings, token_shardings)
                               if plan is not None else None))
            def spec_verify(params, cache, chunk, positions):
                """ONE batched target forward verifying every slot's
                ``k+1`` chunk ``[last_token, d_1..d_k]`` at its own
                positions; ``greedy[i, j]`` is row i's target token after
                its chunk prefix of length j+1. Rows without proposals
                (plain slots, free slots) carry the sentinel position
                past column 0 — their appends drop and only
                ``greedy[i, 0]`` (the ordinary next token) is read."""
                logits, upd = self._step_model.apply(
                    {"params": params, "cache": cache}, chunk, positions,
                    mutable=["cache"])
                return upd["cache"], jnp.argmax(
                    logits, axis=-1).astype(jnp.int32)

            self._spec_verify = spec_verify
            if self._pool is not None:
                gather_view, scatter_tails = (self._gather_view,
                                              self._scatter_tails)

                @functools.partial(
                    jax.jit, donate_argnums=(1,),
                    in_shardings=((plan.params, pool_shardings, _rep,
                                   _rep, _rep, _rep, _rep)
                                  if plan is not None else None),
                    out_shardings=((pool_shardings, token_shardings)
                                   if plan is not None else None))
                def spec_verify_paged(params, pool, tables, chunk,
                                      positions, tail_blocks, tail_pids):
                    """``spec_verify`` over the gathered page view; the
                    k+1 chunk's appends span at most two pages
                    (validated), so the same tail scatter covers them.
                    Rejected proposals' K/V lands in the slot's own
                    PRIVATE tail pages — a rollback can never dirty a
                    shared prefix page."""
                    cache = gather_view(pool, tables)
                    logits, upd = self._step_model.apply(
                        {"params": params, "cache": cache}, chunk,
                        positions, mutable=["cache"])
                    pool = scatter_tails(pool, upd["cache"], tail_blocks,
                                         tail_pids)
                    return pool, jnp.argmax(
                        logits, axis=-1).astype(jnp.int32)

                self._spec_verify_paged = spec_verify_paged

        self._slots: List[Optional[_Slot]] = [None] * n_slots
        self._queue: deque[_Pending] = deque()
        self._kv_queue: deque[_KVPending] = deque()
        self._next_id = 0
        self._finished: Dict[int, np.ndarray] = {}
        self._prefilling: Optional[_Prefilling] = None
        self._reserved_slot: Optional[int] = None
        self._admitting: set = set()   # slots mid-admission (popped from
                                       # the queue, prefill in flight) —
                                       # free_slots must not count them
        self.stats = {"steps": 0, "emitted": 0, "admitted": 0, "crashes": 0,
                      # prefill accounting (the disagg pool-cost signal):
                      # padded positions run through prefill programs, and
                      # how many of those were shared-prefix registrations
                      "prefill_positions": 0, "prefix_prefills": 0,
                      "kv_adopted": 0, "kv_exported": 0,
                      # sharded serving: device→host bytes the KV/prefix
                      # export gathers moved (gather-on-export — the
                      # cross-mesh handoff cost)
                      "export_gather_bytes": 0,
                      # speculative decoding: rounds run, draft tokens
                      # proposed/accepted (their ratio is the acceptance
                      # rate), slot-rounds with >= 1 rejection, draft
                      # crashes
                      # (degrade-to-plain events), and device seconds in
                      # the draft/verify phases on this engine's clock
                      "spec_rounds": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_rollbacks": 0,
                      "draft_crashes": 0,
                      "spec_draft_s": 0.0, "spec_verify_s": 0.0,
                      # admission copy traffic, in cache POSITIONS: dense
                      # admissions copy the request's full cached span
                      # into the pool; paged admissions copy only
                      # freshly-written pages (aliased prefix pages move
                      # a reference, not bytes) — the serve_load --paged
                      # arm's copy-bytes comparison reads these
                      "admit_copy_positions": 0,
                      # paged mode: pages allocated / aliased over the
                      # engine's lifetime, and admissions stalled on an
                      # exhausted pool (the request stays queued)
                      "pages_allocated": 0, "pages_aliased": 0,
                      "admission_stalls": 0,
                      # model hot-swap: params-tree replaces applied
                      # (`replace_params` — the multi-model density path)
                      "param_swaps": 0}
        #: hard bound on requests in flight (queued + prefilling + slots);
        #: ``submit`` past it raises ``EngineOverloadedError``. None keeps
        #: the historical unbounded queue (library use; the gateway bounds
        #: admission itself and runs the engine uncapped).
        self.queue_cap = queue_cap
        #: ``on_retire(request_id, tokens)`` fires (outside the lock) the
        #: moment a request finishes — during ``step()`` OR mid-admission
        #: (instant-eos) — so a wrapping gateway learns completions without
        #: polling ``result()``. Like ``on_token``, a raising callback
        #: detaches with a warning rather than poisoning the batch.
        self._on_retire = on_retire
        # Threading model: ONE driver thread calls step()/run(); submit()
        # and result() may be called concurrently from request-handler
        # threads (the SSE/gRPC frontend shape). This lock serializes the
        # queue/bookkeeping against the driver — device work itself is
        # single-threaded by design.
        self._lock = threading.Lock()

    # ---- model hot-swap ----------------------------------------------------
    def replace_params(self, params, *, quantized: bool = False):
        """Hot-swap the serving parameters: a params-tree REPLACE, never a
        re-init. Every compiled program takes params as an argument, so a
        tree with the identical structure and leaf shapes/dtypes swaps in
        with ZERO recompilation — this is what lets one replica gang host
        several ModelVersion trees (`serve/modelpool.py`) and change the
        active model in milliseconds instead of a process restart.

        The incoming tree rides the ctor's exact preparation path: int8
        conversion when this engine serves int8 (skip with
        ``quantized=True`` if the caller already converted), then the
        shard plan's ``put_params`` when the engine runs on a mesh. The
        same-config-shape contract is ENFORCED — a structure or
        shape/dtype mismatch raises before anything is touched, so the
        previous params always stay live on a refused swap.

        The engine must be idle (no queued, prefilling, or in-slot
        requests): a mid-request swap would splice two models into one
        decode stream. The caller (the model pool's swap scheduler)
        drains first; this check makes the contract self-enforcing.

        Returns the previous (prepared) params tree so the caller can
        keep it resident for the swap back."""
        if self._draft is not None:
            raise ValueError(
                "replace_params on a speculative engine would desync the "
                "draft from the target; model pools run plain engines")
        if self.cfg.serve_int8_weights and not quantized:
            params = quantize_weights_for_serving(params)
        if self._plan is not None:
            params = self._plan.put_params(params)
        old_leaves, old_def = jax.tree.flatten(self._params)
        new_leaves, new_def = jax.tree.flatten(params)
        if new_def != old_def:
            raise ValueError(
                f"replace_params: tree structure mismatch (got {new_def}, "
                f"engine serves {old_def}) — model pools host same-config "
                f"trees only")
        for old, new in zip(old_leaves, new_leaves):
            if old.shape != new.shape or old.dtype != new.dtype:
                raise ValueError(
                    f"replace_params: leaf mismatch {new.shape}/{new.dtype}"
                    f" vs {old.shape}/{old.dtype} — same config shape is "
                    f"the swap contract")
        with self._lock:
            if (self._queue or self._kv_queue
                    or self._prefilling is not None or self._admitting
                    or any(s is not None for s in self._slots)):
                raise RuntimeError(
                    "replace_params on a busy engine: drain in-flight "
                    "requests first (the swap scheduler's job)")
            prev, self._params = self._params, params
            self.stats["param_swaps"] += 1
        return prev

    # ---- paged-pool helpers ------------------------------------------------
    def _count_compile(self) -> None:
        """Every LRU program-cache miss lands here (both modes) — compile
        pressure from a long tail of shapes is a counter, not a mystery."""
        if self.kv_metrics is not None:
            self.kv_metrics.inc("programs_compiled")

    def _pages_for_span(self, end: int) -> int:
        """Block-table entries needed to back positions [0, end)."""
        return -(-end // self.page_tokens)

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages, or None (counted stall — the request stays
        queued and retries next step as pages free up)."""
        pids = self._pool.alloc(n)
        if pids is None:
            self.stats["admission_stalls"] += 1
            if self.kv_metrics is not None:
                self.kv_metrics.inc("admission_stalls")
            return None
        if pids:
            self.stats["pages_allocated"] += len(pids)
            if self.kv_metrics is not None:
                self.kv_metrics.inc("page_allocs", len(pids))
        return pids

    def _alias_pages(self, pids: List[int]) -> List[int]:
        """Reference shared pages into another block table — the
        copy-free half of every prefix-seeded paged admission."""
        self._pool.retain(pids)
        if pids:
            self.stats["pages_aliased"] += len(pids)
            if self.kv_metrics is not None:
                self.kv_metrics.inc("pages_aliased", len(pids))
        return list(pids)

    def _release_pages(self, pages: Optional[List[int]]) -> None:
        if self._pool is not None and pages:
            self._pool.release(pages)

    def _prefix_alias_blocks(self, prefix_id, plen: int) -> List[int]:
        """The shared FULL pages of a registered prefix (every block
        below the fork block ``plen // page``) — what an admission
        aliases instead of copying. Empty when the prefix carries no
        page record (pool exhausted at registration, or no full page
        fits under the fork): the admission then writes every block
        fresh, exactly as correct, just without the sharing win."""
        if prefix_id is None or self._pool is None:
            return []
        pids = self._prefix_pages.get(prefix_id, [])
        fb = plen // self.page_tokens
        return list(pids[:fb]) if len(pids) >= fb else []

    def _paged_admit_fn(self, b: int):
        """Program writing blocks of row ``row`` of a dense [b]-row
        prefill cache into the pool pages named by ``pids`` [nb_total]
        (0 = skip: the write lands in the null page, which the program
        wipes last). One program per prefill batch size, LRU-bounded."""
        def build():
            nb, page = self._nb_total, self.page_tokens
            out_sh = (self._pool_shardings if self._plan is not None
                      else None)

            @functools.partial(jax.jit, donate_argnums=(0,),
                               out_shardings=out_sh)
            def admit_pages(pool, pre_cache, row, pids):
                def write(pl, pre):
                    blocks = pre[:, row].reshape(
                        (pre.shape[0], nb, page) + pre.shape[3:])
                    pl = pl.at[:, pids].set(blocks)
                    return pl.at[:, 0].set(jnp.zeros_like(pl[:, 0]))
                return jax.tree.map(write, pool, _strip_index(pre_cache))
            return admit_pages
        return self._paged_admit_progs.get(b, build)

    def _paged_gather_fn(self, nbp: int):
        """Program gathering ``nbp`` pages into one cursorless batch-1
        row [L, 1, nbp*page, ...] — the paged export path ships only
        REFERENCED pages (table entries past a slot's reservation name
        the null page, so trailing padding is deterministic zeros)."""
        def build():
            page = self.page_tokens

            @jax.jit
            def gather_rows(pool, table):
                def g(pl):
                    x = pl[:, table]           # [L, nbp, page, *rest]
                    return x.reshape((x.shape[0], 1, nbp * page)
                                     + x.shape[3:])
                return jax.tree.map(g, pool)
            return gather_rows
        return self._paged_gather_progs.get(nbp, build)

    def _write_pages(self, pre_cache, row: int,
                     pids_by_block: np.ndarray) -> None:
        """Scatter a dense prefill row into the pool, block by block."""
        b = jax.tree.leaves(_strip_index(pre_cache))[0].shape[1]
        self._pool_cache = self._paged_admit_fn(b)(
            self._pool_cache, pre_cache, jnp.int32(row),
            jnp.asarray(pids_by_block))

    def _table_row(self, pages: List[int]) -> np.ndarray:
        row = np.zeros(self._nb_total, np.int32)
        if pages:
            row[:len(pages)] = pages
        return row

    def _tail_args(self, pos: np.ndarray, span: int):
        """Per-slot tail blocks/pids for a program appending ``span``
        positions starting at each slot's ``pos``: the at-most-two
        blocks the appends can touch (span <= page, validated). Sentinel
        rows (free slots) and unallocated blocks resolve to page 0 —
        their writes land in the null page and are wiped. Host-side
        numpy; two [n_slots, 2] int32 arrays per program call."""
        nb, page = self._nb_total, self.page_tokens
        b0 = np.clip(pos // page, 0, nb - 1)
        b1 = np.clip((pos + span - 1) // page, 0, nb - 1)
        blocks = np.stack([b0, b1], axis=1).astype(np.int32)
        pids = np.take_along_axis(self._tables, blocks, axis=1)
        sentinel = pos >= self.max_len
        blocks[sentinel] = 0
        pids[sentinel] = 0
        return jnp.asarray(blocks), jnp.asarray(pids)

    def _update_page_gauges(self) -> None:
        if self.kv_metrics is not None and self._pool is not None:
            self.kv_metrics.set_gauge("pages_in_use", self._pool.in_use)

    # ---- request lifecycle -------------------------------------------------
    def register_prefix(self, tokens) -> int:
        """Prefill a shared prefix (a system prompt) ONCE and keep its KV
        device-resident; requests submitted with the returned ``prefix_id``
        attend to it without recomputing — each admission prefills only its
        own suffix. RoPE positions are absolute, so the prefix KV (always
        at positions [0, len)) is valid under every continuation. Costs one
        full-length single-request cache pytree of HBM per registered
        prefix, held for the engine's lifetime."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size == 0:
            raise ValueError("empty prefix")
        if tokens.size > self.max_len - 2:
            # every request needs >= 1 prompt token and >= 1 new token on
            # top of the prefix — a longer prefix could never be used
            raise ValueError(f"prefix {tokens.size} leaves no room under "
                             f"max_len {self.max_len}")
        lp = int(tokens.size)
        bucket = _bucket_len(lp, self.max_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :lp] = tokens
        self._rng, key = jax.random.split(self._rng)
        cache, _ = self._prefill_fn(bucket)(
            self._params, jnp.asarray(padded),
            jnp.asarray([lp], np.int32), key)
        self.stats["prefix_prefills"] += 1
        self.stats["prefill_positions"] += bucket
        with self._lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
            self._prefixes[pid] = (cache, lp)
        if self._pool is not None:
            # paged: ALSO write the prefix's full pages into the pool so
            # admissions alias them (refcount, not copy). The partial
            # tail block (positions [fb*page, lp)) stays only in the
            # dense prefix cache — each fork writes its own fork page.
            fb = lp // self.page_tokens
            pids = (self._pool.alloc(fb) if fb else []) or []
            if pids:
                self.stats["pages_allocated"] += len(pids)
                if self.kv_metrics is not None:
                    self.kv_metrics.inc("page_allocs", len(pids))
                row = np.zeros(self._nb_total, np.int32)
                row[:fb] = pids
                self._write_pages(cache, 0, row)
            self._prefix_pages[pid] = pids
            self._update_page_gauges()
        if self._draft is not None:
            # mirror the prefix through the draft so prefix-seeded
            # admissions can seed their draft rows too
            self._draft.register_prefix(pid, tokens)
        return pid

    def export_prefix(self, prefix_id: int):
        """Host copy of a registered prefix's KV: ``(cursorless numpy
        pytree, length)`` — what the fleet prefix store
        (`tpu_on_k8s/serve/kvstore.py`) keeps in its host-RAM overflow
        tier so OTHER replicas can adopt the prefix without recomputing
        its prefill."""
        with self._lock:
            cache, lp = self._prefixes[prefix_id]
        # position-trimmed like export_kv: the overflow tier's host-RAM
        # budget charges for the prefix's bucket, not max_len
        pb = _bucket_len(lp, self.max_len)
        host = _host_leaves(jax.tree.map(
            lambda leaf: leaf[:, :, :pb], _strip_index(cache)))
        # gather-on-export: the host copy is the FULL logical array
        # whatever mesh computed it; account the gathered bytes
        self._export_layout(_cache_nbytes(host))
        return host, lp

    def import_prefix(self, cache, lp: int, base_pid: Optional[int] = None,
                      base_len: int = 0) -> int:
        """Register an already-computed prefix KV (an ``export_prefix``
        host copy from a same-config engine) without running any prefill
        — a host→device copy instead of compute. Returns the new
        prefix id. No token content travels with an export, so a
        speculative engine cannot mirror it through the draft: requests
        using an imported prefix decode on the plain path (exact, just
        unaccelerated).

        Paged engines (``supports_page_alias``) additionally accept
        ``base_pid``/``base_len``: when this prefix EXTENDS an already
        registered ancestor of ``base_len`` positions, the ancestor's
        full pages are aliased into the new prefix's page record instead
        of re-written — a radix-store promote of a descendant prefix
        moves page references, not bytes."""
        lp = int(lp)
        if lp < 1 or lp > self.max_len - 2:
            raise ValueError(f"prefix length {lp} does not fit under "
                             f"max_len {self.max_len}")
        device = _graft_cursorless(init_cache(self._prefill_model, 1), cache)
        if self._plan is not None:
            # reshard-on-import: the export was gathered to the full
            # logical array, so ANY source mesh lands here — lay it out
            # under THIS engine's plan
            device = self._plan.put_cache(device)
        with self._lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
            self._prefixes[pid] = (device, lp)
        if self._pool is not None:
            fb = lp // self.page_tokens
            aliased: List[int] = []
            if base_pid is not None and 0 < base_len <= lp:
                ab = min(base_len // self.page_tokens, fb)
                src = self._prefix_pages.get(base_pid, [])
                if ab and len(src) >= ab:
                    aliased = self._alias_pages(src[:ab])
            fresh_n = fb - len(aliased)
            fresh = self._pool.alloc(fresh_n) if fresh_n else []
            if fresh is None:
                # pool exhausted: fall back to a page-less record — the
                # prefix still works through its dense cache, admissions
                # just write every block fresh
                self._release_pages(aliased)
                pids: List[int] = []
            else:
                if fresh:
                    self.stats["pages_allocated"] += len(fresh)
                    if self.kv_metrics is not None:
                        self.kv_metrics.inc("page_allocs", len(fresh))
                    row = np.zeros(self._nb_total, np.int32)
                    row[len(aliased):fb] = fresh
                    self._write_pages(device, 0, row)
                pids = aliased + fresh
            self._prefix_pages[pid] = pids
            self._update_page_gauges()
        return pid

    def drop_prefix(self, prefix_id: int) -> bool:
        """Release a registered prefix's device KV (the store's demotion
        path — its host copy lives on in the overflow tier). The caller
        owns the invariant that no queued/in-flight request still
        references the id."""
        if self._draft is not None:
            self._draft.drop_prefix(prefix_id)
        if self._pool is not None:
            # refcounted: slots still aliasing these pages keep them
            # live until they retire — only the prefix's own reference
            # drops here
            self._release_pages(self._prefix_pages.pop(prefix_id, None))
            self._update_page_gauges()
        with self._lock:
            return self._prefixes.pop(prefix_id, None) is not None

    def check_request(self, prompt, max_new_tokens: int,
                      prefix_id: Optional[int] = None) -> np.ndarray:
        """Validate a request against this engine's limits WITHOUT
        enqueueing; returns the coerced int32 prompt. The single source
        of these invariants — ``submit`` enforces them through this, and
        the gateway (`tpu_on_k8s/serve/gateway.py`) calls it at admission
        so a request that would fail at dispatch never reserves budget."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        plen = 0
        if prefix_id is not None:
            with self._lock:
                if prefix_id not in self._prefixes:
                    raise ValueError(f"unknown prefix_id {prefix_id}")
                plen = self._prefixes[prefix_id][1]
        if plen + prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prefix {plen} + prompt {prompt.size} + new "
                f"{max_new_tokens} exceeds the engine's max_len "
                f"{self.max_len}")
        if self._pool is not None:
            # a request that alone outsizes the pool would stall the
            # admission loop forever — reject at submission, typed
            fresh = (self._pages_for_span(
                plen + int(prompt.size) + max_new_tokens)
                - plen // self.page_tokens)
            if fresh > self._pool.capacity:
                raise ValueError(
                    f"request needs {fresh} fresh KV pages; the pool "
                    f"holds {self._pool.capacity} (raise kv_pages or "
                    f"shrink the request)")
        return prompt

    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None,
               prefix_id: Optional[int] = None,
               on_token=None) -> int:
        """Enqueue a request; returns its id. ``prompt`` is a 1-D token
        sequence (with ``prefix_id``: the tokens AFTER the registered
        prefix); admission happens on a later ``step()``. ``on_token``
        streams each emitted token as ``on_token(request_id, token)``
        the moment the host sees it (per admission / per horizon) —
        exactly what an SSE/gRPC streaming frontend forwards."""
        prompt = self.check_request(prompt, max_new_tokens, prefix_id)
        with self._lock:
            if self.queue_cap is not None:
                inflight = self._inflight_locked()
                if inflight >= self.queue_cap:
                    raise EngineOverloadedError(inflight, self.queue_cap)
            rid = self._next_id
            self._next_id += 1
            self._queue.append(_Pending(rid, prompt, max_new_tokens,
                                        eos_id, self._clock(),
                                        prefix_id, on_token))
            depth = len(self._queue)
        if self.metrics is not None:
            self.metrics.inc("requests_submitted")
            self.metrics.set_gauge("queue_depth", depth)
        return rid

    def _inflight_locked(self) -> int:
        return (len(self._queue) + len(self._kv_queue)
                + len(self._admitting)
                + sum(s is not None for s in self._slots)
                + (1 if self._prefilling is not None else 0))

    def submit_kv(self, handoff: "KVHandoff", max_new_tokens: int,
                  eos_id: Optional[int] = None,
                  prefix_id: Optional[int] = None,
                  on_token=None) -> int:
        """Enqueue a request whose prefill ALREADY HAPPENED on another
        engine: ``handoff`` carries the KV (`KVHandoff`), so admission is
        a cache splice into a free slot — zero prefill FLOPs here, which
        is the whole point of a dedicated decode pool. ``max_new_tokens``
        is the request's TOTAL budget; the handoff's already-emitted
        tokens count against it (they seed the slot, and are NOT re-fired
        through ``on_token`` — the caller delivered them). A suffix-only
        handoff (``base > 0``) needs ``prefix_id`` naming a locally
        registered prefix of exactly ``base`` positions. The caller
        verifies the transfer checksum (``handoff.verify()``) — this
        method trusts its input."""
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        if not handoff.emitted:
            raise ValueError("handoff carries no emitted tokens")
        if handoff.base > 0:
            if prefix_id is None:
                raise ValueError("suffix-only handoff needs a prefix_id")
            with self._lock:
                if prefix_id not in self._prefixes:
                    raise ValueError(f"unknown prefix_id {prefix_id}")
                plen = self._prefixes[prefix_id][1]
            if plen != handoff.base:
                raise ValueError(f"handoff base {handoff.base} != local "
                                 f"prefix length {plen}")
        remaining = max_new_tokens - len(handoff.emitted)
        if handoff.pos + max(remaining, 0) > self.max_len:
            raise ValueError(
                f"cached {handoff.pos} + remaining {remaining} exceeds "
                f"the engine's max_len {self.max_len}")
        if self._pool is not None:
            fresh = (self._pages_for_span(handoff.pos + max(remaining, 0))
                     - handoff.base // self.page_tokens)
            if fresh > self._pool.capacity:
                raise ValueError(
                    f"handoff needs {fresh} fresh KV pages; the pool "
                    f"holds {self._pool.capacity}")
        with self._lock:
            if self.queue_cap is not None:
                inflight = self._inflight_locked()
                if inflight >= self.queue_cap:
                    raise EngineOverloadedError(inflight, self.queue_cap)
            rid = self._next_id
            self._next_id += 1
            self._kv_queue.append(_KVPending(
                rid, handoff, max_new_tokens, eos_id, prefix_id,
                self._clock(), on_token))
        if self.metrics is not None:
            self.metrics.inc("requests_submitted")
        return rid

    def export_kv(self, request_id: int) -> Optional["KVHandoff"]:
        """Extract a slot-resident request's ACCUMULATED cache (prefix +
        prompt + decoded-so-far) as a sealed host ``KVHandoff`` — adopting
        it on a same-config engine via ``submit_kv`` continues decode
        token-identically (the oracle test in
        `tests/test_serve_disagg.py`). The request keeps running here;
        pair with ``abort()`` to migrate it. ``None`` when the id is not
        currently in a slot (queued / mid-prefill / finished). Driver
        thread only, like ``abort`` — the slot row read must not race a
        running device step."""
        with self._lock:
            found = None
            for i, s in enumerate(self._slots):
                if s is not None and s.request_id == request_id:
                    found = (i, s)
                    break
            if found is None:
                return None
            i, s = found
            pos, emitted = s.pos, tuple(s.emitted)
        # trim to the position bucket of the live positions: the
        # device→host copy, the checksum, and every hop downstream scale
        # with the request, not with max_len (garbage past pos was never
        # data). Pages and buckets share the PAGE_TOKENS granule, so the
        # paged gather ships exactly the bucket's worth of pages.
        pb = _bucket_len(pos, self.max_len)
        if self._pool is not None:
            nbp = pb // self.page_tokens
            row = _host_leaves(self._paged_gather_fn(nbp)(
                self._pool_cache, jnp.asarray(self._tables[i, :nbp])))
        else:
            row = jax.tree.map(
                lambda leaf: np.asarray(leaf[:, i:i + 1, :pb]),
                self._cache)
        self.stats["kv_exported"] += 1
        layout = self._export_layout(_cache_nbytes(row))
        return KVHandoff(cache=row, pos=pos, first_token=emitted[0],
                         emitted=emitted, layout=layout).seal()

    def start_prefill(self, prompt, prefix_id: Optional[int] = None
                      ) -> "PrefillJob":
        """Begin an incremental prefill that ends in a ``KVHandoff``
        instead of a slot admission — the prefill-pool half of
        disaggregated serving. See ``PrefillJob``."""
        prompt = self.check_request(prompt, 1, prefix_id)
        return PrefillJob(self, prompt, prefix_id)

    def _prefill_fn(self, bucket: int, b: int = 1):
        """Prefill ``b`` same-bucket prompts in ONE program: prompts
        [b, bucket], per-row true lengths ``lps`` [b]; returns the [b]-row
        cache plus each row's first token (picked at its own lp-1)."""
        def build():
            model = self._prefill_model
            shapes = cache_shapes(model, b)   # length set by max_len, not lp
            sp = self.sampling
            # per-request prefill caches shard kv-heads on `model` (the
            # admit splice into the pool is then shard-local); sampled
            # first tokens replicate like every per-slot vector
            out_sh = None
            if self._plan is not None:
                out_sh = (self._plan.cache_shardings(shapes),
                          self._plan.replicated)

            @functools.partial(jax.jit, out_shardings=out_sh)
            def prefill(params, prompts, lps, key):
                cache = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), shapes)
                positions = jnp.broadcast_to(
                    jnp.arange(bucket, dtype=jnp.int32), (b, bucket))
                logits, upd = model.apply(
                    {"params": params, "cache": cache}, prompts, positions,
                    mutable=["cache"])
                rows = jnp.arange(b)
                return upd["cache"], _pick(logits[rows, lps - 1], key, sp)

            return prefill

        return self._prefill_cache.get((bucket, b), build)

    def _suffix_prefill_fn(self, bucket: int):
        """Chunked prefill of a request's suffix into a prefix-seeded cache
        (cursor set to the prefix length, so the append lands after the
        prefix and the exact over-cache attention path serves every suffix
        query — it attends the prefix KV without recomputing it)."""
        def build():
            from tpu_on_k8s.models.decode import _set_cursor
            model = self._prefill_model
            sp = self.sampling
            out_sh = None
            if self._plan is not None:
                out_sh = (self._plan.cache_shardings(
                    cache_shapes(model, 1)), self._plan.replicated)

            @functools.partial(jax.jit, out_shardings=out_sh)
            def prefill(params, pre_cache, suffix, plen, slen, key):
                cache = _set_cursor(pre_cache, plen)
                positions = plen + jnp.arange(bucket,
                                              dtype=jnp.int32)[None, :]
                logits, upd = model.apply(
                    {"params": params, "cache": cache}, suffix, positions,
                    mutable=["cache"])
                return upd["cache"], _pick(logits[0, slen - 1], key, sp)

            return prefill

        return self._suffix_prefill_cache.get(bucket, build)

    #: batched-admission program sizes (largest that fits is used); a
    #: bounded set so (bucket, b) programs can't proliferate
    _ADMIT_BATCH_SIZES = (4, 2, 1)

    def _admit_kv_pending(self) -> None:
        """Adopt queued KV handoffs into free slots — before the regular
        queue: a handed-off request already paid its prefill (and its
        queue wait on the prefill pool), and its splice costs no prefill
        program, so it never starves prompt admissions of device time."""
        while True:
            with self._lock:
                if not self._kv_queue:
                    return
                free = [i for i in range(self.n_slots)
                        if self._slots[i] is None
                        and i != self._reserved_slot
                        and i not in self._admitting]
                if not free:
                    return
                req = self._kv_queue[0]
                pages: Optional[List[int]] = None
                fb = 0
                if self._pool is not None:
                    # eager reservation: the splice must never fail after
                    # the request leaves the queue. A short pool stalls
                    # the adoption (counted) until pages free up.
                    h = req.handoff
                    remaining = max(req.max_new_tokens - len(h.emitted), 0)
                    alias = (self._prefix_alias_blocks(req.prefix_id,
                                                       h.base)
                             if h.base > 0 else [])
                    fb = len(alias)
                    fresh = self._alloc_pages(
                        self._pages_for_span(h.pos + remaining) - fb)
                    if fresh is None:
                        return
                    pages = self._alias_pages(alias) + fresh
                self._kv_queue.popleft()
                self._admitting.add(free[0])
            i = free[0]
            try:
                self._adopt_into_slot(i, req, pages, fb)
            except BaseException:
                self._release_pages(pages)
                raise
            finally:
                with self._lock:
                    self._admitting.discard(i)

    def _adopt_into_slot(self, i: int, req: _KVPending,
                         pages: Optional[List[int]] = None,
                         fb: int = 0) -> None:
        """Splice a handoff's KV into slot ``i`` and activate it. A
        suffix-only handoff lays its rows over the locally registered
        prefix's (identical bytes to what the prefill replica attended —
        same params, same tokens, same compiled programs). Paged mode:
        the leading ``fb`` entries of ``pages`` alias the prefix's full
        pages (already counted); only fork + handoff blocks are
        written."""
        h = req.handoff
        # reshard-on-import: a handoff from an UNLIKE mesh (or a
        # single-program prefill engine) carries the gathered full
        # array; this engine lays it out under its own plan
        device = (self._plan.put_cache(h.cache) if self._plan is not None
                  else jax.tree.map(jnp.asarray, h.cache))
        pb = jax.tree.leaves(device)[0].shape[2]
        if self._pool is not None:
            nbp = self._pages_for_span(h.pos)
            # stage a dense batch-1 row: prefix bytes below the fork
            # (from the local dense prefix copy), handoff rows [base,
            # pos) overlaid — then scatter only blocks [fb, nbp) into
            # this slot's fresh pages
            if h.base > 0:
                staged_base = _strip_index(self._prefixes[req.prefix_id][0])
            else:
                staged_base = _strip_index(
                    init_cache(self._prefill_model, 1))
            base, pos = h.base, h.pos

            def overlay(baseleaf, hleaf):
                pad = baseleaf.shape[2] - hleaf.shape[2]
                if pad > 0:
                    hleaf = jnp.pad(
                        hleaf, [(0, 0), (0, 0), (0, pad)]
                        + [(0, 0)] * (hleaf.ndim - 3))
                span = jnp.arange(baseleaf.shape[2]).reshape(
                    (1, -1) + (1,) * (hleaf.ndim - 3))
                keep = (span >= base) & (span < pos)
                return jnp.where(keep, hleaf, baseleaf)

            staged = jax.tree.map(overlay, staged_base, device)
            pids_row = np.zeros(self._nb_total, np.int32)
            for j in range(fb, nbp):
                pids_row[j] = pages[j]
            self._write_pages(staged, 0, pids_row)
            self._tables[i] = self._table_row(pages)
            self.stats["admit_copy_positions"] += ((nbp - fb)
                                                   * self.page_tokens)
            self._update_page_gauges()
        else:
            if h.base > 0:
                prefix_cache = self._prefixes[req.prefix_id][0]
                self._cache = self._admit(self._cache, prefix_cache,
                                          jnp.int32(i), jnp.int32(h.base),
                                          jnp.int32(0))
            self._cache = self._admit_range_for(pb)(
                self._cache, device, jnp.int32(i),
                jnp.int32(h.base), jnp.int32(h.pos), jnp.int32(0))
            self.stats["admit_copy_positions"] += h.pos
        with self._lock:
            self._slots[i] = _Slot(req.request_id, h.pos,
                                   int(h.emitted[-1]), list(h.emitted),
                                   req.max_new_tokens, req.eos_id,
                                   req.submitted_at, req.on_token,
                                   pages=pages)
        # pre-emitted tokens are NOT re-fired or re-counted: the prefill
        # engine emitted them and the handoff's owner delivered them
        self.stats["admitted"] += 1
        self.stats["kv_adopted"] += 1
        if self.metrics is not None:
            self.metrics.set_gauge("queue_depth", len(self._queue))
        self._retire_if_done(i)

    def _admit_pending(self) -> None:
        if self._prefilling is not None:
            self._advance_prefill()       # one chunk per engine step
        self._admit_kv_pending()
        with self._lock:
            # bound this pass to the arrivals present at entry: under
            # concurrent submitters an unbounded while-queue loop could
            # admit-and-retire forever (instant-eos floods) and starve
            # the decode section below
            budget = len(self._queue)
        while budget > 0:
            # selection runs under the lock (frontend threads append to
            # the queue concurrently — iterating/popping must not race
            # them); device work happens after release
            with self._lock:
                if not self._queue:
                    return
                free = [i for i in range(self.n_slots)
                        if self._slots[i] is None
                        and i != self._reserved_slot
                        and i not in self._admitting]
                if not free:
                    return
                req = self._queue[0]
                prefix_cache, plen = ((None, 0) if req.prefix_id is None
                                      else self._prefixes[req.prefix_id])
                chunked = (self.prefill_chunk
                           and req.prompt.size > self.prefill_chunk)
                if chunked and self._prefilling is not None:
                    return    # strict FIFO: one chunked prefill in flight
                if chunked or prefix_cache is not None:
                    head_pages: Optional[List[int]] = None
                    fresh_from = 0
                    if self._pool is not None:
                        # eager reservation: pages for the whole span
                        # [0, plen+prompt+max_new) are claimed BEFORE the
                        # request leaves the queue, so admission can
                        # never half-fail. Full prefix pages alias (CoW:
                        # the fork block is always written fresh).
                        alias = (self._prefix_alias_blocks(req.prefix_id,
                                                           plen)
                                 if prefix_cache is not None else [])
                        fresh_from = len(alias)
                        end = plen + int(req.prompt.size) \
                            + req.max_new_tokens
                        fresh = self._alloc_pages(
                            self._pages_for_span(end) - fresh_from)
                        if fresh is None:
                            return    # pool short: stall, retry next step
                        head_pages = self._alias_pages(alias) + fresh
                    self._queue.popleft()
                    if chunked:
                        # reserve under the lock: free_slots must never
                        # overcount while the chunked prefill is staged
                        self._reserved_slot = free[0]
                    else:
                        self._admitting.add(free[0])
                    group = [req]
                    group_pages = ([head_pages]
                                   if head_pages is not None else None)
                else:
                    # plain requests: batch the front FIFO run sharing
                    # this request's prompt bucket into ONE prefill
                    # program — a burst pays one dispatch, not one per
                    # request
                    bucket = _bucket_len(int(req.prompt.size),
                                         self.max_len)
                    group = [req]
                    for nxt in itertools.islice(
                            self._queue, 1, self._ADMIT_BATCH_SIZES[0]):
                        if (len(group) >= min(len(free),
                                              self._ADMIT_BATCH_SIZES[0])
                                or nxt.prefix_id is not None
                                or (self.prefill_chunk
                                    and (nxt.prompt.size
                                         > self.prefill_chunk))
                                or _bucket_len(int(nxt.prompt.size),
                                               self.max_len) != bucket):
                            break
                        group.append(nxt)
                    b = max(s for s in self._ADMIT_BATCH_SIZES
                            if s <= min(len(group), len(free)))
                    group = group[:b]
                    group_pages = None
                    if self._pool is not None:
                        # eager per-request reservation bounds the batch
                        # by what the pool can actually hold
                        group_pages = []
                        for r in group:
                            fresh = self._alloc_pages(
                                self._pages_for_span(
                                    int(r.prompt.size)
                                    + r.max_new_tokens))
                            if fresh is None:
                                break
                            group_pages.append(fresh)
                        if not group_pages:
                            return    # head stalled on the pool
                        if len(group_pages) < len(group):
                            b = max(s for s in self._ADMIT_BATCH_SIZES
                                    if s <= len(group_pages))
                            for pl in group_pages[b:]:
                                self._release_pages(pl)
                            group = group[:b]
                            group_pages = group_pages[:b]
                    for _ in group:
                        self._queue.popleft()
                    self._admitting.update(free[:len(group)])
                depth = len(self._queue)
            budget -= len(group)
            if chunked:
                if self.metrics is not None:
                    self.metrics.set_gauge("queue_depth", depth)
                pre_cache = (prefix_cache if prefix_cache is not None
                             else init_cache(self._prefill_model, 1))
                self._prefilling = _Prefilling(
                    req, pre_cache, plen, plen,
                    plen + int(req.prompt.size), self._clock(),
                    pages=group_pages[0] if group_pages else None,
                    fresh_from=fresh_from if group_pages else 0)
                self._advance_prefill()
                continue
            unconsumed = list(group_pages) if group_pages else []
            try:
                if prefix_cache is not None:
                    dequeued_at = self._clock()
                    slen = int(req.prompt.size)
                    self._rng, key = jax.random.split(self._rng)
                    # the suffix bucket may not spill past max_len:
                    # appends land at plen..plen+bucket-1
                    # (dynamic_update_slice would clamp a spilling start
                    # and corrupt earlier rows)
                    bucket = _bucket_len(slen, self.max_len - plen)
                    padded = np.zeros((1, bucket), np.int32)
                    padded[0, :slen] = req.prompt
                    pre_cache, first = self._suffix_prefill_fn(bucket)(
                        self._params, prefix_cache, jnp.asarray(padded),
                        jnp.int32(plen), jnp.int32(slen), key)
                    self.stats["prefill_positions"] += bucket
                    pages = unconsumed.pop(0) if unconsumed else None
                    self._finish_admission(free[0], req, pre_cache, first,
                                           plen + slen, dequeued_at,
                                           pages=pages,
                                           fresh_from=fresh_from)
                    continue
                b = len(group)
                dequeued_at = self._clock()
                lps = np.asarray([r.prompt.size for r in group], np.int32)
                padded = np.zeros((b, bucket), np.int32)
                for j, r in enumerate(group):
                    padded[j, :r.prompt.size] = r.prompt
                self._rng, key = jax.random.split(self._rng)
                pre_cache, firsts = self._prefill_fn(bucket, b)(
                    self._params, jnp.asarray(padded), jnp.asarray(lps),
                    key)
                self.stats["prefill_positions"] += bucket * b
                firsts = np.asarray(firsts)
                for j, (r, i) in enumerate(zip(group, free)):
                    pages = unconsumed.pop(0) if unconsumed else None
                    self._finish_admission(i, r, pre_cache, firsts[j],
                                           int(lps[j]), dequeued_at,
                                           row=j, pages=pages)
            finally:
                # a failing prefill must not leak reservations or pages
                # (success clears each slot in _finish_admission and
                # drains ``unconsumed`` as rows land)
                for pl in unconsumed:
                    self._release_pages(pl)
                with self._lock:
                    self._admitting.difference_update(free)

    def _advance_prefill(self) -> None:
        """One chunk of the in-flight chunked prefill: append this chunk's
        KV to the request's private cache via the (exact) cursor-seeded
        suffix program; on the last chunk, sample the first token and
        admit into the reserved slot."""
        st = self._prefilling
        offset = st.done - st.base
        chunk = st.req.prompt[offset:offset + self.prefill_chunk]
        clen = int(chunk.size)
        bucket = _bucket_len(clen, self.max_len - st.done)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :clen] = chunk
        self._rng, key = jax.random.split(self._rng)
        st.pre_cache, first = self._suffix_prefill_fn(bucket)(
            self._params, st.pre_cache, jnp.asarray(padded),
            jnp.int32(st.done), jnp.int32(clen), key)
        self.stats["prefill_positions"] += bucket
        st.done += clen
        if st.done == st.total:
            i = self._reserved_slot
            self._prefilling = None
            # fill the slot first, then drop the reservation — the brief
            # filled+reserved overlap UNDERcounts free_slots (safe for
            # admission control); the reverse order would overcount
            self._finish_admission(i, st.req, st.pre_cache, first,
                                   st.total, st.dequeued_at,
                                   pages=st.pages,
                                   fresh_from=st.fresh_from)
            with self._lock:
                self._reserved_slot = None

    def _finish_admission(self, i: int, req: _Pending, pre_cache, first,
                          lp: int, dequeued_at: float,
                          row: int = 0,
                          pages: Optional[List[int]] = None,
                          fresh_from: int = 0) -> None:
        """Copy row ``row`` of a prefilled cache into slot ``i`` and
        activate it; the first token (already sampled by the prefill
        program) is emitted here. Paged mode scatters only the blocks
        past ``fresh_from`` (aliased prefix pages are already live)."""
        if self._pool is not None:
            wb = self._pages_for_span(lp)
            pids_row = np.zeros(self._nb_total, np.int32)
            for j in range(fresh_from, wb):
                pids_row[j] = pages[j]
            self._write_pages(pre_cache, row, pids_row)
            self._tables[i] = self._table_row(pages)
            self.stats["admit_copy_positions"] += ((wb - fresh_from)
                                                   * self.page_tokens)
            self._update_page_gauges()
        else:
            self._cache = self._admit(self._cache, pre_cache,
                                      jnp.int32(i), jnp.int32(lp),
                                      jnp.int32(row))
            self.stats["admit_copy_positions"] += lp
        first = int(first)   # host sync: the first token IS emitted now
        drafted = False
        if self._draft is not None:
            # seed the slot's draft row from the request's own tokens —
            # one cheap draft prefill (the draft never chunks; its whole
            # prompt fits one bucketed call). False (an imported-prefix
            # id the draft never saw) leaves the slot on plain decode.
            drafted = self._draft.seed(i, req.prompt, req.prefix_id)
        with self._lock:
            self._slots[i] = _Slot(req.request_id, lp, first, [first],
                                   req.max_new_tokens, req.eos_id,
                                   req.submitted_at, req.on_token,
                                   draft=drafted, pages=pages)
            self._admitting.discard(i)
        self._fire_on_token(self._slots[i], first)
        self.stats["admitted"] += 1
        self.stats["emitted"] += 1
        if self.metrics is not None:
            self.metrics.observe("queue_wait_seconds",
                                 dequeued_at - req.submitted_at)
            self.metrics.observe("time_to_first_token_seconds",
                                 self._clock() - req.submitted_at)
            self.metrics.inc("tokens_emitted")
            self.metrics.set_gauge("queue_depth", len(self._queue))
        self._retire_if_done(i)

    def _fire_on_token(self, slot: _Slot, token: int) -> None:
        """Streaming callbacks run between device steps — a raising
        callback (e.g. a disconnected SSE client) must not unwind the
        engine loop mid-horizon, or OTHER slots' host state desyncs from
        the already-advanced device cache. Detach it, count it, keep
        serving."""
        if slot.on_token is None:
            return
        try:
            slot.on_token(slot.request_id, token)
        except Exception as e:  # noqa: BLE001 — isolate per-request faults
            slot.on_token = None
            from tpu_on_k8s.metrics.metrics import count_detached_callback
            count_detached_callback(
                self.metrics,
                f"on_token callback for request {slot.request_id} raised "
                f"{type(e).__name__}: {e}; streaming detached")

    def _retire_if_done(self, i: int) -> bool:
        slot = self._slots[i]
        done = (len(slot.emitted) >= slot.max_new_tokens
                or (slot.eos_id is not None
                    and slot.emitted[-1] == slot.eos_id))
        if done:
            tokens = np.asarray(slot.emitted, np.int32)
            with self._lock:
                self._finished[slot.request_id] = tokens
                self._slots[i] = None
            if self._pool is not None:
                self._release_pages(slot.pages)
                self._tables[i, :] = 0
                self._update_page_gauges()
            if self.metrics is not None:
                self.metrics.inc("requests_finished")
                self.metrics.observe("request_latency_seconds",
                                     self._clock() - slot.submitted_at)
            if self._on_retire is not None:
                try:
                    self._on_retire(slot.request_id, tokens)
                except Exception as e:  # noqa: BLE001 — isolate like on_token
                    self._on_retire = None
                    from tpu_on_k8s.metrics.metrics import (
                        count_detached_callback,
                    )
                    count_detached_callback(
                        self.metrics,
                        f"on_retire callback raised {type(e).__name__}: "
                        f"{e}; detached")
        return done

    def abort(self, request_id: int) -> Optional[np.ndarray]:
        """Abort a request wherever it lives — queued, mid-chunked-prefill,
        or mid-decode — and free its capacity immediately: a decoding
        request's slot is host-side bookkeeping, so the very next ``step()``
        runs without it and can admit a waiting request into the freed slot
        (its stale KV rows are never attended and are overwritten on reuse,
        the same invariant slot retirement relies on).

        Returns the tokens emitted so far (empty for a request that never
        reached a slot) or ``None`` when the id is unknown, already
        finished, or mid-admission this instant (popped from the queue with
        its prefill in flight — retryable on the next step). Call from the
        driver thread only: concurrent with a running ``step()`` it could
        null a slot the decode loop is reading. The gateway
        (`tpu_on_k8s/serve/gateway.py`) honors this by marking cancels from
        frontend threads and aborting at the top of its own step."""
        with self._lock:
            for idx, p in enumerate(self._queue):
                if p.request_id == request_id:
                    del self._queue[idx]
                    if self.metrics is not None:
                        self.metrics.set_gauge("queue_depth",
                                               len(self._queue))
                    return np.zeros(0, np.int32)
            for idx, p in enumerate(self._kv_queue):
                if p.request_id == request_id:
                    del self._kv_queue[idx]
                    # the handoff's tokens were already delivered by its
                    # owner — partial, like a mid-decode abort
                    return np.asarray(p.handoff.emitted, np.int32)
            st = self._prefilling
            if st is not None and st.req.request_id == request_id:
                # drop the private prefill cache and the slot reservation;
                # nothing reached the shared pool yet (reserved pages go
                # straight back)
                self._prefilling = None
                self._reserved_slot = None
                self._release_pages(st.pages)
                self._update_page_gauges()
                return np.zeros(0, np.int32)
            for i, s in enumerate(self._slots):
                if s is not None and s.request_id == request_id:
                    self._slots[i] = None
                    if self._pool is not None:
                        self._release_pages(s.pages)
                        self._tables[i, :] = 0
                        self._update_page_gauges()
                    return np.asarray(s.emitted, np.int32)
        return None

    def reset(self) -> List[int]:
        """Recover the engine after a crash (``EngineCrashError``): drop all
        host-side request state — slots, queue, chunked prefill, admission
        reservations — as a restarted decode worker would come up empty.
        The compiled programs, parameters, registered prefixes, and the
        device cache pool survive (stale cache rows are never attended and
        are overwritten on the next admission — the same invariant slot
        retirement relies on). Already-finished results stay claimable.
        In-flight requests are LOST here by design; the returned ids are
        everything dropped, so the caller (the gateway's replay machinery)
        can re-admit its own and account for any it does not own."""
        with self._lock:
            lost = [p.request_id for p in self._queue]
            lost += [p.request_id for p in self._kv_queue]
            if self._prefilling is not None:
                lost.append(self._prefilling.req.request_id)
            lost += [s.request_id for s in self._slots if s is not None]
            if self._pool is not None:
                # per-request pages go back to the pool; registered
                # prefixes keep theirs (they survive the crash too)
                for s in self._slots:
                    if s is not None:
                        self._release_pages(s.pages)
                if self._prefilling is not None:
                    self._release_pages(self._prefilling.pages)
                self._tables[:, :] = 0
            self._slots = [None] * self.n_slots
            self._queue.clear()
            self._kv_queue.clear()
            self._prefilling = None
            self._reserved_slot = None
            self._admitting.clear()
        self._update_page_gauges()
        if self.metrics is not None:
            self.metrics.set_gauge("queue_depth", 0)
            self.metrics.set_gauge("slots_active", 0)
        return sorted(lost)

    # ---- the engine loop ---------------------------------------------------
    def step(self) -> List[int]:
        """Admit queued requests, advance every active slot by one horizon
        (``step_horizon`` tokens in one compiled program), and return the
        ids of requests that finished. The ids are NOTIFICATIONS — the
        payload is claimed by whoever calls ``result()`` first, so pick
        ONE consumer per request (the driver loop or a polling frontend
        thread, not both) and treat ``result() is None`` as
        already-claimed."""
        fault = chaos.fire(chaos.SITE_SERVE_STEP, steps=self.stats["steps"])
        if fault is not None:
            if isinstance(fault, chaos.EngineCrash):
                self.stats["crashes"] += 1
                raise EngineCrashError("chaos: engine crashed mid-decode")
            if isinstance(fault, chaos.EngineStall):
                # a wedged device step: no admission, no tokens, no
                # retirement — the caller's own timeout machinery (gateway
                # drain deadline) is the only way out
                return []
        # snapshot BEFORE admission: a request that retires during
        # admission itself (max_new_tokens=1, instant eos) must still be
        # reported by THIS step, or a step()/result() driver never learns
        # it finished
        with self._lock:
            before = set(self._finished)
        self._admit_pending()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if active and self._use_spec_round(active):
            self._spec_round(active)
        elif active:
            toks = np.zeros(self.n_slots, np.int32)
            pos = np.full(self.n_slots, self.max_len, np.int32)  # sentinel
            for i in active:
                toks[i] = self._slots[i].last_token
                pos[i] = self._slots[i].pos
            self._rng, key = jax.random.split(self._rng)
            if self._pool is not None:
                tb, tp = self._tail_args(pos, self.step_horizon)
                self._pool_cache, out = self._step_paged(
                    self._params, self._pool_cache,
                    jnp.asarray(self._tables), jnp.asarray(toks),
                    jnp.asarray(pos), tb, tp, key)
            else:
                self._cache, out = self._step(self._params, self._cache,
                                              jnp.asarray(toks),
                                              jnp.asarray(pos), key)
            out = np.asarray(out)               # [horizon, n_slots]
            self.stats["steps"] += self.step_horizon
            emitted_now = 0
            for i in active:
                emitted_now += self._emit_tokens(i, out[:, i])
            if self.metrics is not None:
                self.metrics.inc("tokens_emitted", emitted_now)
        if self.metrics is not None:
            self.metrics.set_gauge(
                "slots_active",
                sum(s is not None for s in self._slots))
        with self._lock:
            return sorted(set(self._finished) - before)

    def _emit_tokens(self, i: int, tokens) -> int:
        """Append host-side ``tokens`` to slot ``i`` in order: position,
        bookkeeping, streaming, and retirement are ONE sequence shared by
        the plain horizon loop and the speculative rounds — the two
        decode paths cannot diverge on emission semantics. Stops at
        retirement (surplus tokens are discarded, greedy output is
        unchanged); returns the count actually emitted."""
        n = 0
        for tok in tokens:
            slot = self._slots[i]
            slot.pos += 1
            slot.last_token = int(tok)
            slot.emitted.append(slot.last_token)
            self.stats["emitted"] += 1
            n += 1
            self._fire_on_token(slot, slot.last_token)
            if self._retire_if_done(i):
                break
        return n

    def _use_spec_round(self, active: List[int]) -> bool:
        """True when this step should run a speculative round: a draft is
        attached, at least one active slot is drafted (an all-undrafted
        pool — e.g. a disagg decode replica serving only adopted
        handoffs — takes the plain step rather than paying the
        (k+1)-wide verify to emit one token per slot), and the draft
        survives this round's chaos injection."""
        if self._draft is None:
            return False
        if not any(self._slots[i].draft for i in active):
            return False
        fault = chaos.fire(chaos.SITE_SPEC_DRAFT,
                           rounds=self.stats["spec_rounds"])
        if isinstance(fault, chaos.DraftCrash):
            self.degrade_draft()
            return False
        return True

    def degrade_draft(self) -> None:
        """Drop a dead draft model and keep serving: every in-flight
        request continues on the plain decode path from this very step,
        token-identically (greedy — the draft is an accelerator, never a
        correctness dependency). Counted, never silent. Raised by chaos
        (``DraftCrash``); an external supervisor translating a real
        draft-worker death should call it too so recovery stays typed."""
        self._draft = None
        self.stats["draft_crashes"] += 1
        if self.spec_metrics is not None:
            self.spec_metrics.inc("spec_draft_crashes")
        import warnings
        warnings.warn("speculative draft crashed; engine degraded to "
                      "plain decode (token-identical, nothing lost)",
                      stacklevel=3)

    def _spec_round(self, active: List[int]) -> None:
        """One speculative round over the whole slot pool: the draft
        proposes ``k`` greedy tokens per drafted slot in one scanned
        program, ONE batched target forward verifies every slot's
        ``[last_token, d_1..d_k]`` chunk, and each row emits its longest
        agreeing prefix plus the target's correction/bonus token — 1 to
        ``k+1`` tokens per row per round, token-identical to plain greedy
        decode. Undrafted rows (adopted handoffs, imported prefixes)
        ride the same programs at the sentinel position and emit exactly
        their ordinary next token (``_use_spec_round`` guarantees at
        least one drafted row — an all-undrafted pool takes the plain
        step instead). Rollback is position bookkeeping only — see
        ``_DraftRunner``."""
        k = self._spec_k
        t0 = self._clock()
        toks = np.zeros(self.n_slots, np.int32)
        pos = np.full(self.n_slots, self.max_len, np.int32)   # sentinel
        for i in active:
            s = self._slots[i]
            toks[i] = s.last_token
            if s.draft:
                pos[i] = s.pos
        proposals = self._draft.propose(toks, pos)
        t1 = self._clock()
        chunk = np.zeros((self.n_slots, k + 1), np.int32)
        cpos = np.full((self.n_slots, k + 1), self.max_len, np.int32)
        for i in active:
            s = self._slots[i]
            chunk[i, 0] = s.last_token
            cpos[i, 0] = s.pos
            if s.draft:
                chunk[i, 1:] = proposals[:, i]
                cpos[i] = s.pos + np.arange(k + 1, dtype=np.int32)
        # no rng split: spec mode is greedy-only by construction, so no
        # key is ever consumed (and degrade-to-plain stays greedy too)
        if self._pool is not None:
            # the k+1 chunk spans ≤2 tail pages (spec_k+1 ≤ page,
            # checked at construction); rejected proposals' KV lands in
            # the slot's OWN tail pages, so rollback stays pure position
            # bookkeeping even with aliased prefix pages below the fork
            tb, tp = self._tail_args(cpos[:, 0], k + 1)
            self._pool_cache, greedy = self._spec_verify_paged(
                self._params, self._pool_cache,
                jnp.asarray(self._tables), jnp.asarray(chunk),
                jnp.asarray(cpos), tb, tp)
        else:
            self._cache, greedy = self._spec_verify(
                self._params, self._cache, jnp.asarray(chunk),
                jnp.asarray(cpos))
        greedy = np.asarray(greedy)                    # [n_slots, k+1]
        t2 = self._clock()
        self.stats["steps"] += 1
        rids = sorted(self._slots[i].request_id for i in active)
        emitted_now = proposed = accepted_n = rollbacks = 0
        for i in active:
            s = self._slots[i]
            if s.draft:
                j = 0
                while j < k and proposals[j, i] == greedy[i, j]:
                    j += 1
                out = [int(proposals[x, i]) for x in range(j)]
                out.append(int(greedy[i, j]))   # correction (bonus at j=k)
                proposed += k
                accepted_n += j
                if j < k:
                    rollbacks += 1
            else:
                out = [int(greedy[i, 0])]
            emitted_now += self._emit_tokens(i, out)
        self.stats["spec_rounds"] += 1
        self.stats["spec_proposed"] += proposed
        self.stats["spec_accepted"] += accepted_n
        self.stats["spec_rollbacks"] += rollbacks
        self.stats["spec_draft_s"] += t1 - t0
        self.stats["spec_verify_s"] += t2 - t1
        if self.spec_metrics is not None and proposed:
            self.spec_metrics.inc("spec_tokens_proposed", proposed)
            if accepted_n:
                self.spec_metrics.inc("spec_tokens_accepted", accepted_n)
            if rollbacks:
                self.spec_metrics.inc("spec_rollbacks", rollbacks)
            self.spec_metrics.set_gauge(
                "spec_acceptance_rate",
                self.stats["spec_accepted"]
                / max(self.stats["spec_proposed"], 1))
        if self.metrics is not None:
            self.metrics.inc("tokens_emitted", emitted_now)
        if self._on_spec_round is not None:
            try:
                self._on_spec_round(rids, t1 - t0, t2 - t1, proposed,
                                    accepted_n)
            except Exception as e:  # noqa: BLE001 — isolate like on_retire
                self._on_spec_round = None
                from tpu_on_k8s.metrics.metrics import (
                    count_detached_callback,
                )
                count_detached_callback(
                    self.metrics,
                    f"on_spec_round callback raised {type(e).__name__}: "
                    f"{e}; detached")

    def run(self) -> Dict[int, np.ndarray]:
        """Drain the queue and every active slot; returns {id: tokens}."""
        while (self._queue or self._kv_queue
               or self._prefilling is not None
               or any(s is not None for s in self._slots)):
            self.step()
        out, self._finished = self._finished, {}
        return out

    def result(self, request_id: int) -> Optional[np.ndarray]:
        """The finished continuation for ``request_id`` (None if still in
        flight); pops it from the engine. Thread-safe (frontend threads
        poll while the driver steps)."""
        with self._lock:
            return self._finished.pop(request_id, None)

    @property
    def free_slots(self) -> int:
        with self._lock:
            free = sum(s is None for s in self._slots)
            return (free - len(self._admitting)
                    - (1 if self._reserved_slot is not None else 0))

    # ---- sharded-serving observability --------------------------------------
    def _export_layout(self, nbytes: int) -> CacheLayout:
        """The layout block every KV/prefix export carries, plus the
        gather-on-export accounting: the device→host copy materializes
        the FULL logical array (all heads, all positions) whatever this
        engine's mesh — that is what makes the payload adoptable on any
        unlike mesh, and these are the bytes that cost."""
        self.stats["export_gather_bytes"] += nbytes
        if self.shard_metrics is not None:
            self.shard_metrics.inc("export_gather_bytes", nbytes)
        return CacheLayout(mesh_axes=dict(self.mesh_axes),
                           gathered_bytes=nbytes)

    @property
    def param_bytes_per_chip(self) -> int:
        """Serving-tree bytes each chip holds (= total bytes on a
        single-program engine; shrinks with the `model`/`expert` axes on
        a mesh) — the headroom number that says how big a model THIS
        replica shape can hold."""
        if self._plan is not None:
            return self._plan.bytes_per_chip(self._params)
        return sum(int(leaf.nbytes)
                   for leaf in jax.tree.leaves(self._params))

    @property
    def kv_bytes_per_chip(self) -> int:
        """Slot-pool KV bytes per chip (kv-heads split over `model`,
        slots — or pages — over `data`); registered prefixes are charged
        separately by the prefix store."""
        pool = self._pool_cache if self._pool is not None else self._cache
        if self._plan is not None:
            return self._plan.bytes_per_chip(pool)
        return _cache_nbytes(pool)

    def shard_report(self) -> Dict[str, Any]:
        """One-line shard accounting for tools (`serve_load --shard`)
        and tests: mesh axes, chip count, and per-chip vs total
        param/KV bytes."""
        total_params = sum(int(leaf.nbytes)
                           for leaf in jax.tree.leaves(self._params))
        return {
            "mesh_axes": dict(self.mesh_axes),
            "n_chips": self.n_chips,
            "param_bytes_per_chip": self.param_bytes_per_chip,
            "param_bytes_total": total_params,
            "kv_bytes_per_chip": self.kv_bytes_per_chip,
            "kv_bytes_total": _cache_nbytes(
                self._pool_cache if self._pool is not None
                else self._cache),
        }


def _zero_below(leaf: np.ndarray, base: int) -> np.ndarray:
    """Zero a cache leaf's positions < ``base`` (axis 2 — the same axis
    the admit programs span): a suffix-only handoff transfers nothing it
    expects the adopting engine to supply, and its checksum covers
    exactly the transferred bytes."""
    out = np.array(leaf)
    out[:, :, :base] = 0
    return out


class PrefillJob:
    """Incremental prefill that ends in a ``KVHandoff`` instead of a slot
    admission — the prefill-pool half of disaggregated serving
    (`tpu_on_k8s/serve/disagg.py`).

    ``advance()`` runs ONE chunk per call (``engine.prefill_chunk``
    positions when chunking is on; otherwise the whole prompt), mirroring
    exactly the admission path a monolithic engine with the same config
    would take — same programs, same bucketing, same chunk boundaries —
    so decode from the handed-off KV is oracle-identical to monolithic
    decode. The job drives the engine's prefill programs directly and
    never touches the slot pool; one job at a time per engine is the
    caller's discipline (the disagg fleet runs one per prefill replica,
    matching the engine's own one-chunked-prefill-in-flight rule).

    With ``prefix_id`` the job prefills only the suffix over the
    registered prefix's cache (the fleet-wide dedup win: the shared
    prefix's prefill already happened, possibly on another replica via
    the `FleetPrefixStore`)."""

    def __init__(self, engine: ContinuousBatchingEngine, prompt: np.ndarray,
                 prefix_id: Optional[int]) -> None:
        self._engine = engine
        self.prompt = prompt
        self.prefix_id = prefix_id
        if prefix_id is not None:
            with engine._lock:
                cache, base = engine._prefixes[prefix_id]
            # never mutated: the suffix program is functional and the
            # cursor re-seed rebuilds leaves
            self._cache = cache
        else:
            base = 0
            self._cache = None
        self.base = base
        self.done = base                   # positions cached so far
        self.total = base + int(prompt.size)
        self.first_token: Optional[int] = None

    @property
    def finished(self) -> bool:
        return self.first_token is not None

    @property
    def remaining(self) -> int:
        return self.total - self.done

    def advance(self) -> bool:
        """Prefill one chunk; returns True once the whole prompt is
        cached (``first_token`` is then the prefill's sampled token)."""
        if self.finished:
            return True
        eng = self._engine
        chunked = (eng.prefill_chunk
                   and self.prompt.size > eng.prefill_chunk)
        if not chunked and self.base == 0:
            # whole-prompt, no prefix: the monolithic cold-admission path
            lp = int(self.prompt.size)
            bucket = _bucket_len(lp, eng.max_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :lp] = self.prompt
            eng._rng, key = jax.random.split(eng._rng)
            self._cache, firsts = eng._prefill_fn(bucket)(
                eng._params, jnp.asarray(padded),
                jnp.asarray([lp], np.int32), key)
            eng.stats["prefill_positions"] += bucket
            self.done = self.total
            self.first_token = int(np.asarray(firsts)[0])
            eng.stats["emitted"] += 1
            return True
        if self._cache is None:
            self._cache = init_cache(eng._prefill_model, 1)
        offset = self.done - self.base
        chunk = (self.prompt[offset:offset + eng.prefill_chunk]
                 if chunked else self.prompt[offset:])
        clen = int(chunk.size)
        bucket = _bucket_len(clen, eng.max_len - self.done)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :clen] = chunk
        eng._rng, key = jax.random.split(eng._rng)
        self._cache, first = eng._suffix_prefill_fn(bucket)(
            eng._params, self._cache, jnp.asarray(padded),
            jnp.int32(self.done), jnp.int32(clen), key)
        eng.stats["prefill_positions"] += bucket
        self.done += clen
        if self.done == self.total:
            self.first_token = int(first)
            eng.stats["emitted"] += 1
        return self.finished

    def handoff(self, *, suffix_only: bool = False,
                prefix_hash: Optional[str] = None) -> KVHandoff:
        """Export the finished prefill as a sealed host ``KVHandoff``.
        ``suffix_only`` (with a prefix-seeded job) strips the shared
        prefix's rows — the adopting engine supplies them from its own
        registered copy of ``prefix_hash``, so only suffix bytes cross
        the wire."""
        if not self.finished:
            raise RuntimeError("prefill is not finished")
        # position-trimmed like export_kv: payload bytes track the
        # request's bucket, not max_len
        pb = _bucket_len(self.total, self._engine.max_len)
        host = _host_leaves(jax.tree.map(
            lambda leaf: leaf[:, :, :pb], _strip_index(self._cache)))
        base = 0
        if suffix_only and self.base > 0:
            base = self.base
            host = jax.tree.map(lambda leaf: _zero_below(leaf, base), host)
        layout = self._engine._export_layout(_cache_nbytes(host))
        return KVHandoff(cache=host, pos=self.total,
                         first_token=self.first_token,
                         emitted=(self.first_token,), base=base,
                         prefix_hash=prefix_hash, layout=layout).seal()
