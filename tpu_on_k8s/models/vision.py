"""Vision models: ResNet-50 (the headline images/sec benchmark) + MNIST CNN.

These are the compute-plane counterparts of the reference's sample jobs
(BASELINE.json configs: "MNIST CNN, 1-master TorchJob" and "ResNet-50 DDP,
1 master + 2 workers" — the reference itself ships no model code, its
training math lived in user containers, SURVEY.md §2.10).

TPU-first choices:
* NHWC layout — XLA:TPU's native conv layout; convs tile straight onto the MXU.
* bf16 compute / fp32 params and batch-norm statistics.
* BatchNorm running stats live in a separate ``batch_stats`` collection,
  handled by ``ClassifierTrainer`` (`tpu_on_k8s/train/vision.py`); stats are
  synchronized across data shards with ``axis_name``-free mean (XLA inserts
  the cross-replica reduction from the sharding, so no explicit pmean).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_on_k8s.parallel.mesh import AXIS_FSDP
from tpu_on_k8s.parallel.partition import PartitionRule


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck with projection shortcut when shapes change."""

    features: int               # bottleneck width; output is 4x
    strides: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=self.param_dtype)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, epsilon=1e-5, dtype=jnp.float32,
                     param_dtype=jnp.float32)
        out_feats = self.features * 4
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = nn.relu(bn(name="bn1")(y).astype(self.dtype))
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides),
                 padding="SAME", name="conv2")(y)
        y = nn.relu(bn(name="bn2")(y).astype(self.dtype))
        y = conv(out_feats, (1, 1), name="conv3")(y)
        y = bn(name="bn3", scale_init=nn.initializers.zeros)(y).astype(self.dtype)
        if residual.shape[-1] != out_feats or self.strides > 1:
            residual = conv(out_feats, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj_conv")(residual)
            residual = bn(name="proj_bn")(residual).astype(self.dtype)
        return nn.relu(y + residual)


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @staticmethod
    def resnet50(num_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig(num_classes=num_classes)

    @staticmethod
    def resnet18ish(num_classes: int = 10) -> "ResNetConfig":
        """Small test shape (still bottleneck blocks)."""
        return ResNetConfig(stage_sizes=(1, 1), num_classes=num_classes,
                            width=16)


class ResNet(nn.Module):
    """ResNet-v1.5 with bottleneck blocks. __call__([B,H,W,C] images, train)
    → [B, num_classes] fp32 logits."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="bn_init")(x)
        x = nn.relu(x.astype(cfg.dtype))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(cfg.width * (2 ** stage), strides,
                               cfg.dtype, cfg.param_dtype,
                               name=f"stage{stage}_block{block}")(x, train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))   # global avg pool
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, name="head")(x)


class MnistCNN(nn.Module):
    """The reference's config/samples MNIST shape: 2 convs + 2 dense."""

    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), dtype=self.dtype, name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype, name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(128, dtype=self.dtype, name="dense1")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def vision_partition_rules() -> List[PartitionRule]:
    """Mostly data-parallel: conv kernels shard output channels over fsdp
    (ZeRO-style weight sharding — all-gathered per layer by XLA), norms and
    small heads replicate."""
    return [
        PartitionRule(r"bn|norm|bias|scale", P()),
        PartitionRule(r"head/kernel", P(AXIS_FSDP, None)),
        PartitionRule(r"conv.*/kernel", P(None, None, None, AXIS_FSDP)),
        PartitionRule(r"dense.*/kernel", P(None, AXIS_FSDP)),
    ]
