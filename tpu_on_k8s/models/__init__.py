"""Model zoo for the compute plane — one model per BASELINE.json config.

Currently implemented:

* ``transformer``— Llama-style flagship (7B FSDP multi-queue config), the
                   model behind ``__graft_entry__.py``.

Planned (tracked against BASELINE.json): ``mnist_cnn``, ``resnet`` (ResNet-50),
``bert``, ``gpt2``.
"""
from tpu_on_k8s.models.transformer import (
    Transformer,
    TransformerConfig,
    flagship_partition_rules,
)

__all__ = ["Transformer", "TransformerConfig", "flagship_partition_rules"]
