"""Token sampling: greedy, temperature, top-k, nucleus (top-p).

One jit-traceable sampler shared by every serving path — ``generate()``
(`tpu_on_k8s/models/decode.py`), the continuous-batching engine
(`tpu_on_k8s/models/serving.py`) — so a sampling change can never apply
to one path and not another. All operations are static-shape (sort +
mask, no dynamic gather sizes), exactly what XLA wants on TPU.

The reference operator never samples tokens (it schedules pods); this is
the compute plane's own surface.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Hashable (usable in jit cache keys) sampling configuration.

    ``temperature <= 0`` is greedy argmax and ignores the rest. ``top_k``
    keeps the k highest logits; ``top_p`` keeps the smallest set of
    tokens whose probability mass reaches p (the first token always
    survives). Both filters compose: top-k first, then top-p over the
    renormalized survivors — the common (vLLM/HF) convention.
    """

    temperature: float = 0.0
    top_k: int = 0        # 0 = off
    top_p: float = 0.0    # 0 or 1 = off (values outside [0, 1] rejected)

    def __post_init__(self):
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {self.top_p}")

    @property
    def is_greedy(self) -> bool:
        return self.temperature <= 0.0


_NEG = -1e30


def _top_k_mask(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask all but EXACTLY the k highest logits per row to -inf (ties
    truncate by index, inheriting jax.lax.top_k's order; k beyond the
    vocabulary clamps — the HF/vLLM convention)."""
    k = min(k, logits.shape[-1])
    _, idx = jax.lax.top_k(logits, k)                       # [..., k]
    keep = jax.nn.one_hot(idx, logits.shape[-1],
                          dtype=jnp.bool_).any(axis=-2)     # [..., V]
    return jnp.where(keep, logits, _NEG)


def _top_p_mask(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filter: keep the smallest prefix of the probability-sorted
    vocabulary whose mass reaches ``p``; the top token always survives."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # a token is kept if the mass BEFORE it is < p (so the token that
    # crosses the threshold is included)
    keep_sorted = (cum - probs) < p
    # threshold logit = smallest kept logit; everything below drops
    kth = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                  axis=-1, keepdims=True)
    return jnp.where(logits >= kth, logits, _NEG)


def sample(logits: jnp.ndarray, key: jax.Array,
           params: SamplingParams) -> jnp.ndarray:
    """Next token per row of ``logits [..., V]`` under ``params``."""
    if params.is_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / params.temperature
    if params.top_k:
        logits = _top_k_mask(logits, params.top_k)
    if 0.0 < params.top_p < 1.0:
        logits = _top_p_mask(logits, params.top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
