"""BERT-family bidirectional encoder with an MLM head, TPU-first.

Covers the "BERT-base pretrain, gang MinMember=4" benchmark config from
BASELINE.json (the reference shipped no model code — SURVEY.md §2.10). Same
hardware-driven construction as the flagship decoder
(`tpu_on_k8s/models/transformer.py`): nn.scan over layers for O(1) compile
time in depth, bf16 matmuls / fp32 statistics, partition rules external to
the model, non-causal attention through the same pluggable kernel selection
(plain XLA or the Pallas flash kernel).
"""
from __future__ import annotations

import dataclasses
from typing import Any, List

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_on_k8s.models.transformer import _select_attention
from tpu_on_k8s.parallel.mesh import AXIS_FSDP, AXIS_MODEL
from tpu_on_k8s.parallel.partition import PartitionRule


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    attn_impl: str = "xla"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny() -> "BertConfig":
        return BertConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                          d_ff=128, max_seq_len=128)


class EncoderBlock(nn.Module):
    """Post-LN transformer encoder block (the BERT arrangement)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, attention_mask=None):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=True, name=name, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02))
        ln = lambda name: nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                                       param_dtype=cfg.param_dtype, name=name)
        b, l = x.shape[0], x.shape[1]
        q = dense(cfg.d_model, "wq")(x).reshape(b, l, cfg.n_heads, cfg.head_dim)
        k = dense(cfg.d_model, "wk")(x).reshape(b, l, cfg.n_heads, cfg.head_dim)
        v = dense(cfg.d_model, "wv")(x).reshape(b, l, cfg.n_heads, cfg.head_dim)
        if attention_mask is not None:
            # padding mask [B, L] (1 = real token) expressed as SEGMENTS:
            # real tokens share segment 0, each pad gets a unique sentinel
            # — so the mask rides the configured attention impl (including
            # the Pallas flash kernel's in-VMEM segment operand) instead
            # of a bespoke quadratic branch
            if cfg.attn_impl not in ("xla", "flash"):
                raise ValueError("attention_mask needs the xla or flash "
                                 "attention path")
            idx = jnp.arange(l, dtype=jnp.int32)[None, :]
            seg = jnp.where(attention_mask.astype(bool), 0, -(idx + 1))
            attn = _select_attention(cfg.attn_impl)(q, k, v, causal=False,
                                                    segments=seg)
        else:
            attn = _select_attention(cfg.attn_impl)(q, k, v, causal=False)
        attn = dense(cfg.d_model, "wo")(attn.reshape(b, l, cfg.d_model))
        x = ln("attn_norm")(x + attn).astype(cfg.dtype)
        h = dense(cfg.d_ff, "w_fc")(x)
        # BERT's published activation is the exact (erf) gelu, not the
        # tanh approximation — matters for HF checkpoint parity
        h = dense(cfg.d_model, "w_proj")(nn.gelu(h, approximate=False))
        x = ln("mlp_norm")(x + h).astype(cfg.dtype)
        return x, None


class Bert(nn.Module):
    """__call__([B, L] token ids, [B, L] type ids?) → [B, L, vocab] MLM logits."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray,
                 type_ids: jnp.ndarray = None,
                 attention_mask: jnp.ndarray = None) -> jnp.ndarray:
        cfg = self.cfg
        embed = self.param("embed", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (cfg.max_seq_len, cfg.d_model), cfg.param_dtype)
        typ = self.param("type_embed", nn.initializers.normal(0.02),
                         (cfg.type_vocab_size, cfg.d_model), cfg.param_dtype)
        l = tokens.shape[1]
        if type_ids is None:
            type_ids = jnp.zeros_like(tokens)
        x = (jnp.take(embed, tokens, axis=0) + pos[None, :l]
             + jnp.take(typ, type_ids, axis=0))
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="embed_norm")(x)
        x = x.astype(cfg.dtype)

        block_cls = nn.remat(EncoderBlock, prevent_cse=False) if cfg.remat \
            else EncoderBlock
        stack = nn.scan(
            block_cls,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(cfg, name="blocks")
        x, _ = stack(x, attention_mask)

        # MLM head: transform (dense + erf-gelu) + LN + tied-embedding
        # projection — the exact BERT arrangement (HF's
        # BertPredictionHeadTransform applies the activation between the
        # dense and the LayerNorm).
        x = nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="mlm_transform")(x)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="mlm_norm")(x)
        bias = self.param("mlm_bias", nn.initializers.zeros,
                          (cfg.vocab_size,), cfg.param_dtype)
        logits = jnp.einsum("bld,vd->blv", x.astype(cfg.dtype),
                            embed.astype(cfg.dtype),
                            preferred_element_type=jnp.float32)
        return logits + bias[None, None, :]


def bert_partition_rules() -> List[PartitionRule]:
    """Megatron layout over the scan-stacked encoder params."""
    return [
        PartitionRule(r"w[qkv]/kernel", P(None, AXIS_FSDP, AXIS_MODEL)),
        PartitionRule(r"wo/kernel", P(None, AXIS_MODEL, AXIS_FSDP)),
        PartitionRule(r"w_fc/kernel", P(None, AXIS_FSDP, AXIS_MODEL)),
        PartitionRule(r"w_proj/kernel", P(None, AXIS_MODEL, AXIS_FSDP)),
        PartitionRule(r"(^|/)embed$", P(AXIS_MODEL, AXIS_FSDP)),
        PartitionRule(r"pos_embed|type_embed", P(None, AXIS_FSDP)),
        PartitionRule(r"mlm_transform/kernel", P(AXIS_FSDP, AXIS_MODEL)),
        PartitionRule(r"norm|bias", P()),
    ]


def mlm_loss(logits: jnp.ndarray, targets: jnp.ndarray,
             mask: jnp.ndarray) -> jnp.ndarray:
    """Masked-LM CE: mean over positions where ``mask`` is 1."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
