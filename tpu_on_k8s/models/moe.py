"""Mixture-of-Experts MLP with expert parallelism over the ``expert`` axis.

Switch/GShard-style top-k routing with per-expert capacity, written as dense
dispatch/combine einsums: the expert dimension of the weights is sharded over
the mesh ``expert`` axis (partition rules in
`tpu_on_k8s/models/transformer.py`), so XLA's SPMD partitioner derives the
token all-to-all from the shardings — no hand-written collective, per the
scaling-book recipe. The reference has no model code at all; this is a
capability extension of the TPU compute plane.

Capacity bookkeeping follows the GShard algorithm: per (group, expert) slots
are assigned in token order via a cumulative sum; overflowing tokens are
dropped (their residual path carries them). A load-balance auxiliary loss is
``sow``n into the ``losses`` collection; the Trainer folds it into the
objective when ``aux_loss_weight > 0``.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense MLP block. x: [B, L, D] → [B, L, D]."""

    cfg: Any  # TransformerConfig with n_experts > 0

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        e, k = cfg.n_experts, cfg.experts_top_k
        b, l, d = x.shape
        capacity = max(1, int(cfg.expert_capacity_factor * k * l / e))

        router_kernel = self.param("router", nn.initializers.normal(0.02),
                                   (d, e), jnp.float32)
        # routing in fp32: small matmul, numerically sensitive
        logits = jnp.einsum("bld,de->ble", x.astype(jnp.float32),
                            router_kernel)                   # [B, L, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k dispatch with capacity, GShard-style
        remaining = probs
        fill = jnp.zeros((b, e), jnp.int32)                  # slots used so far
        dispatch = jnp.zeros((b, l, e, capacity), x.dtype)
        combine = jnp.zeros((b, l, e, capacity), jnp.float32)
        for _ in range(k):
            choice = jnp.argmax(remaining, axis=-1)          # [B, L]
            gate = jnp.take_along_axis(remaining, choice[..., None],
                                       axis=-1)[..., 0]      # [B, L]
            onehot_e = jax.nn.one_hot(choice, e, dtype=jnp.int32)
            # slot index per token: tokens earlier in the sequence win
            pos = fill[:, None, :] + jnp.cumsum(onehot_e, axis=1) - onehot_e
            slot = jnp.sum(pos * onehot_e, axis=-1)          # [B, L]
            keep = slot < capacity
            onehot_c = jax.nn.one_hot(slot, capacity)        # [B, L, C]
            mask = (onehot_e.astype(x.dtype)[:, :, :, None]
                    * onehot_c.astype(x.dtype)[:, :, None, :]
                    * keep[:, :, None, None].astype(x.dtype))
            dispatch = dispatch + mask
            combine = combine + mask.astype(jnp.float32) * gate[:, :, None, None]
            fill = fill + jnp.sum(onehot_e, axis=1)
            remaining = remaining * (1.0 - onehot_e.astype(jnp.float32))

        # load-balance loss (Switch eq. 4): E · Σ_e f_e · P_e
        token_frac = jnp.mean(
            (jnp.sum(dispatch, axis=-1) > 0).astype(jnp.float32), axis=(0, 1))
        prob_frac = jnp.mean(probs, axis=(0, 1))
        self.sow("losses", "load_balance",
                 e * jnp.sum(token_frac * prob_frac))

        # expert compute; weights stacked [E, D, F] — sharded over the
        # `expert` axis by the partition rules, which makes XLA turn the
        # dispatch einsum into an all-to-all over ICI. ``mlp_int8`` routes
        # the expert matmuls through the batched SwitchBack path (expert dim
        # stays a dot batch dim, so the sharding story is unchanged).
        init = nn.initializers.normal(0.02)
        if getattr(cfg, "mlp_int8", False):
            from tpu_on_k8s.ops.int8_matmul import int8_matmul_batched
            emm = int8_matmul_batched
        else:
            # contract x's last dim with w's dim 1, expert dim batched —
            # covers both the up ([E,D,F]) and down ([E,F,D]) orientations
            emm = lambda a, w: jnp.einsum("ebcx,exy->ebcy", a, w)
        w_up = self.param("w_up", init, (e, d, cfg.d_ff), cfg.param_dtype)
        w_down = self.param("w_down", init, (e, cfg.d_ff, d), cfg.param_dtype)
        expert_in = jnp.einsum("blec,bld->ebcd", dispatch,
                               x)                            # [E, B, C, D]
        if cfg.activation == "gelu":
            h = nn.gelu(emm(expert_in, w_up.astype(cfg.dtype)))
        else:
            w_gate = self.param("w_gate", init, (e, d, cfg.d_ff),
                                cfg.param_dtype)
            h = nn.silu(emm(expert_in, w_gate.astype(cfg.dtype))) * emm(
                expert_in, w_up.astype(cfg.dtype))
        out = emm(h, w_down.astype(cfg.dtype))
        return jnp.einsum("ebcd,blec->bld", out,
                          combine.astype(cfg.dtype))
