// Native host-side data pipeline for TPU training.
//
// The host CPU must keep the chip fed: batch assembly off the Python thread,
// prefetching into a bounded queue, and shard-aware deterministic shuffling.
// The reference delegated its data path to user containers (it is a Go
// operator — SURVEY.md §2); this is the TPU-native runtime equivalent, in
// C++ as a plain C-ABI shared library consumed via ctypes
// (tpu_on_k8s/data/loader.py).
//
// Design:
//  * Dataset = mmap'd flat file of fixed-size records (tokenized sequences,
//    serialized examples, ...). Zero deserialization cost; the kernel's page
//    cache is the working set.
//  * Sharding is strided: host shard s of N owns records {i*N + s}. Every
//    shard sees per_shard = n/N records; the ragged tail is dropped so all
//    SPMD hosts take the same number of steps.
//  * Shuffling is a keyed Feistel permutation over [0, per_shard) with
//    cycle-walking — O(1) state, random access, bit-exact reproducible from
//    (seed, epoch) on any host and in the pure-Python fallback.
//  * Workers claim batch tickets from an atomic counter and deposit into a
//    slot ring (slot = ticket % prefetch); the consumer drains in ticket
//    order, so output order is deterministic regardless of worker count.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Dataset {
  int fd = -1;
  size_t size = 0;
  const char* data = nullptr;
  int64_t record_bytes = 0;
  int64_t n_records = 0;
};

inline uint32_t mix(uint32_t x, uint32_t key) {
  x ^= key;
  x *= 0x9E3779B1u;
  x ^= x >> 16;
  x *= 0x85EBCA77u;
  x ^= x >> 13;
  return x;
}

// Keyed Feistel permutation over [0, m) via cycle-walking on 2*half_bits.
struct Feistel {
  uint64_t m;
  uint32_t half_bits;
  uint32_t keys[4];

  Feistel(uint64_t m_, uint64_t seed, uint64_t epoch) : m(m_) {
    uint32_t bits = 1;
    while ((1ull << bits) < m_) bits++;
    half_bits = (bits + 1) / 2;
    for (uint32_t r = 0; r < 4; r++) {
      keys[r] = mix(static_cast<uint32_t>(seed ^ (seed >> 32)) + r * 0x1000193u,
                    static_cast<uint32_t>(epoch) * 0x01000193u + 0x811C9DC5u + r);
    }
  }

  uint64_t operator()(uint64_t x) const {
    if (m <= 1) return 0;
    const uint64_t mask = (1ull << half_bits) - 1;
    do {
      uint64_t left = x >> half_bits, right = x & mask;
      for (uint32_t r = 0; r < 4; r++) {
        uint64_t next = left ^ (mix(static_cast<uint32_t>(right), keys[r]) & mask);
        left = right;
        right = next;
      }
      x = (left << half_bits) | right;
    } while (x >= m);
    return x;
  }
};

struct Slot {
  std::vector<char> buf;
  int64_t ticket = -1;  // -1 = free
};

struct Loader {
  Dataset* ds = nullptr;
  int64_t batch_size = 0;
  int64_t shard = 0, num_shards = 1;
  int64_t seed = 0;
  bool shuffle = true;
  int64_t per_shard = 0;
  int64_t batches_per_epoch = 0;

  std::atomic<int64_t> next_ticket{0};
  int64_t consumer_pos = 0;
  bool stopping = false;

  std::vector<Slot> slots;
  std::mutex mu;
  std::condition_variable cv_producer, cv_consumer;
  std::vector<std::thread> workers;

  void fill(int64_t ticket, std::vector<char>* out) const {
    const int64_t epoch = ticket / batches_per_epoch;
    const int64_t batch_idx = ticket % batches_per_epoch;
    Feistel perm(per_shard, static_cast<uint64_t>(seed),
                 static_cast<uint64_t>(epoch));
    const int64_t rb = ds->record_bytes;
    for (int64_t j = 0; j < batch_size; j++) {
      int64_t local = batch_idx * batch_size + j;
      if (shuffle) local = static_cast<int64_t>(perm(static_cast<uint64_t>(local)));
      const int64_t global = local * num_shards + shard;
      std::memcpy(out->data() + j * rb, ds->data + global * rb, rb);
    }
  }

  void worker_loop() {
    const size_t cap = slots.size();
    while (true) {
      const int64_t ticket = next_ticket.fetch_add(1);
      std::vector<char> buf(static_cast<size_t>(batch_size * ds->record_bytes));
      fill(ticket, &buf);
      std::unique_lock<std::mutex> lock(mu);
      Slot& slot = slots[static_cast<size_t>(ticket) % cap];
      cv_producer.wait(lock, [&] {
        return stopping ||
               (slot.ticket == -1 &&
                ticket < consumer_pos + static_cast<int64_t>(cap));
      });
      if (stopping) return;
      slot.buf = std::move(buf);
      slot.ticket = ticket;
      cv_consumer.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* tk_open(const char* path, int64_t record_bytes) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0 ||
      st.st_size % record_bytes != 0) {
    ::close(fd);
    return nullptr;
  }
  void* data = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                    MAP_PRIVATE, fd, 0);
  if (data == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* ds = new Dataset();
  ds->fd = fd;
  ds->size = static_cast<size_t>(st.st_size);
  ds->data = static_cast<const char*>(data);
  ds->record_bytes = record_bytes;
  ds->n_records = st.st_size / record_bytes;
  return ds;
}

int64_t tk_num_records(void* handle) {
  return static_cast<Dataset*>(handle)->n_records;
}

void tk_close(void* handle) {
  auto* ds = static_cast<Dataset*>(handle);
  munmap(const_cast<char*>(ds->data), ds->size);
  ::close(ds->fd);
  delete ds;
}

// start_ticket resumes the deterministic stream mid-run in O(1): tickets
// are absolute (epoch = ticket / batches_per_epoch), so a checkpointed
// consumer position replays nothing and skips nothing.
void* tk_loader_start(void* dataset, int64_t batch_size, int64_t shard,
                      int64_t num_shards, int64_t seed,
                      int64_t start_ticket, int32_t shuffle,
                      int32_t num_workers, int32_t prefetch) {
  auto* ds = static_cast<Dataset*>(dataset);
  const int64_t per_shard = ds->n_records / num_shards;
  if (per_shard < batch_size || batch_size <= 0 || start_ticket < 0)
    return nullptr;
  auto* ld = new Loader();
  ld->ds = ds;
  ld->batch_size = batch_size;
  ld->shard = shard;
  ld->num_shards = num_shards;
  ld->seed = seed;
  ld->shuffle = shuffle != 0;
  ld->per_shard = per_shard;
  ld->batches_per_epoch = per_shard / batch_size;
  ld->next_ticket = start_ticket;
  ld->consumer_pos = start_ticket;
  ld->slots.resize(static_cast<size_t>(prefetch > 0 ? prefetch : 2));
  for (int32_t w = 0; w < (num_workers > 0 ? num_workers : 1); w++) {
    ld->workers.emplace_back([ld] { ld->worker_loop(); });
  }
  return ld;
}

int64_t tk_batches_per_epoch(void* loader) {
  return static_cast<Loader*>(loader)->batches_per_epoch;
}

// Blocks until the next in-order batch is ready, copies it into `out`
// (batch_size * record_bytes bytes). Returns 1 when a batch was written,
// 0 when the loader is stopping and `out` was left untouched — the caller
// must not treat the buffer as a batch in that case.
int32_t tk_next(void* loader, char* out) {
  auto* ld = static_cast<Loader*>(loader);
  const size_t cap = ld->slots.size();
  std::unique_lock<std::mutex> lock(ld->mu);
  Slot& slot = ld->slots[static_cast<size_t>(ld->consumer_pos) % cap];
  ld->cv_consumer.wait(lock, [&] {
    return ld->stopping || slot.ticket == ld->consumer_pos;
  });
  if (ld->stopping) return 0;
  std::memcpy(out, slot.buf.data(), slot.buf.size());
  slot.ticket = -1;
  ld->consumer_pos++;
  ld->cv_producer.notify_all();
  return 1;
}

void tk_loader_stop(void* loader) {
  auto* ld = static_cast<Loader*>(loader);
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->stopping = true;
  }
  ld->cv_producer.notify_all();
  ld->cv_consumer.notify_all();
  for (auto& t : ld->workers) t.join();
  delete ld;
}

}  // extern "C"
