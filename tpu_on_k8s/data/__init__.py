"""Host-side data pipeline: native C++ loader + pure-Python fallback."""
from tpu_on_k8s.data.loader import (  # noqa: F401
    DataLoader,
    FixedRecordDataset,
    feistel_permutation,
    native_available,
    write_records,
)
from tpu_on_k8s.data.packing import pack_greedy, pack_stream  # noqa: F401
