"""Host→device prefetch: overlap the next batch's H2D copy with compute.

The native loader (`tpu_on_k8s/data/loader.py`) assembles batches on worker
threads; this generator keeps ``depth`` batches ahead of the training loop as
*sharded device arrays*, so the `jax.device_put` (DMA to HBM) of batch N+1
runs while step N computes. The standard flax prefetch pattern, applied to
the framework's own loader and shardings.
"""
from __future__ import annotations

import collections
from typing import Callable, Iterable, Iterator, Optional

import jax


def device_prefetch(batches: Iterable, sharding, depth: int = 2,
                    transform: Optional[Callable] = None) -> Iterator:
    """Yield device-resident batches, keeping ``depth`` in flight.

    ``sharding`` is a NamedSharding (e.g. ``batch_sharding(mesh, shape)``) or
    a pytree of them matching each batch's structure. ``transform`` runs on
    host (numpy) before the transfer — e.g. normalize / split image+label.
    """
    queue = collections.deque()
    it = iter(batches)

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                batch = next(it)
            except StopIteration:
                return
            if transform is not None:
                batch = transform(batch)
            queue.append(jax.tree.map(
                lambda leaf: jax.device_put(leaf, sharding), batch)
                if not isinstance(batch, tuple) else
                tuple(jax.device_put(leaf, sharding) for leaf in batch))

    enqueue(depth)
    while queue:
        out = queue.popleft()
        enqueue(1)
        yield out
