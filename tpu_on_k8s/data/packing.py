"""Pack ragged tokenized documents into fixed-length training windows.

The loader (`tpu_on_k8s/data/loader.py`) serves fixed-size records —
what the static-shape training step wants — but real corpora are ragged
documents. Two standard packing strategies:

* ``"stream"`` (GPT-2 style): concatenate every document with an EOS
  separator into one token stream and slice it into windows. Zero
  padding waste; documents may straddle window boundaries (the causal LM
  objective tolerates the context bleed, and this is how most
  pretraining corpora are packed).
* ``"greedy"`` (no-split): first-fit documents whole into windows,
  EOS-separated, padding each window's tail with ``pad_id``. No
  cross-document bleed mid-window at the cost of padding waste; the
  returned mask weights real tokens for the loss.

Both are pure NumPy — run once at corpus-prep time, then
``write_records`` the result for the mmap'd loader.
"""
from __future__ import annotations

from typing import Iterable, Optional, Tuple

import numpy as np


def pack_stream(docs: Iterable[np.ndarray], seq_len: int,
                eos_id: int) -> np.ndarray:
    """[n, seq_len] windows sliced from the EOS-joined document stream;
    the ragged tail (< seq_len tokens) is dropped."""
    pieces = []
    for d in docs:
        d = np.asarray(d, np.int32).reshape(-1)
        pieces.append(d)
        pieces.append(np.asarray([eos_id], np.int32))
    if not pieces:
        return np.zeros((0, seq_len), np.int32)
    stream = np.concatenate(pieces)
    n = stream.size // seq_len
    return stream[:n * seq_len].reshape(n, seq_len).copy()


def pack_greedy(docs: Iterable[np.ndarray], seq_len: int, eos_id: int,
                pad_id: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """First-fit whole-document packing: ``(windows [n, seq_len],
    mask [n, seq_len])`` with 1 marking real (non-pad) tokens. Documents
    longer than ``seq_len - 1`` (a doc plus its EOS must fit) are
    rejected — split such docs upstream or use ``pack_stream``."""
    pad = eos_id if pad_id is None else pad_id
    eos = np.asarray([eos_id], np.int32)
    windows = []            # list of lists of doc arrays (joined at the end)
    remaining = []          # free capacity per window — the fit scan works
                            # on plain ints, not materialized token lists
    for d in docs:
        d = np.asarray(d, np.int32).reshape(-1)
        need = d.size + 1   # the doc and its EOS separator
        if need > seq_len:
            raise ValueError(
                f"document of {d.size} tokens cannot fit a {seq_len} "
                f"window whole; split it upstream or use pack_stream")
        for i, cap in enumerate(remaining):
            if need <= cap:
                windows[i] += [d, eos]
                remaining[i] = cap - need
                break
        else:
            windows.append([d, eos])
            remaining.append(seq_len - need)
    out = np.full((len(windows), seq_len), pad, np.int32)
    mask = np.zeros((len(windows), seq_len), np.int32)
    for i, parts in enumerate(windows):
        w = np.concatenate(parts)
        out[i, :w.size] = w
        mask[i, :w.size] = 1
    return out, mask
