"""Shard-aware deterministic data loading for SPMD training.

Wraps the native C++ pipeline (`tpu_on_k8s/data/native/dataloader.cpp` —
threaded batch assembly, bounded prefetch queue, mmap'd records) behind a
NumPy-facing ``DataLoader``. The shared library is compiled on first use with
the baked-in g++ (no pip); when no compiler is available a pure-Python
fallback runs the *same* keyed-Feistel permutation bit-exactly, so batch
order is identical either way — what every SPMD host needs to agree on.

Dataset format: a flat binary file of fixed-size records. ``write_records``
produces it from a NumPy array; anything that can mmap flat records
(tokenized corpora, packed examples) works.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

_NATIVE_DIR = Path(__file__).parent / "native"
_SRC = _NATIVE_DIR / "dataloader.cpp"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build_lib() -> Optional[ctypes.CDLL]:
    so = _NATIVE_DIR / "build" / "libtkdata.so"
    so.parent.mkdir(exist_ok=True)
    if not so.exists() or so.stat().st_mtime < _SRC.stat().st_mtime:
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               "-o", str(so), str(_SRC), "-lpthread"]
        try:
            # analyze: allow[lock-order] the module build lock EXISTS to serialize this one-time g++ compile; it is bounded (timeout=120) and first-import-only
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            return None
    lib = ctypes.CDLL(str(so))
    lib.tk_open.restype = ctypes.c_void_p
    lib.tk_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.tk_num_records.restype = ctypes.c_int64
    lib.tk_num_records.argtypes = [ctypes.c_void_p]
    lib.tk_close.argtypes = [ctypes.c_void_p]
    lib.tk_loader_start.restype = ctypes.c_void_p
    lib.tk_loader_start.argtypes = [ctypes.c_void_p] + [ctypes.c_int64] * 5 + \
        [ctypes.c_int32] * 3
    lib.tk_batches_per_epoch.restype = ctypes.c_int64
    lib.tk_batches_per_epoch.argtypes = [ctypes.c_void_p]
    lib.tk_next.restype = ctypes.c_int32
    lib.tk_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tk_loader_stop.argtypes = [ctypes.c_void_p]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is None and not _lib_failed:
            _lib = _build_lib()
            _lib_failed = _lib is None
        return _lib


def native_available() -> bool:
    return _get_lib() is not None


# ---------------------------------------------------------------------------
# the Feistel permutation, mirrored bit-exactly from dataloader.cpp
# ---------------------------------------------------------------------------

def _mix(x: int, key: int) -> int:
    x = (x ^ key) & 0xFFFFFFFF
    x = (x * 0x9E3779B1) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA77) & 0xFFFFFFFF
    x ^= x >> 13
    return x


def feistel_permutation(m: int, seed: int, epoch: int) -> "_Feistel":
    """Keyed bijection over [0, m) — identical output to the C++ pipeline."""
    return _Feistel(m, seed, epoch)


class _Feistel:
    def __init__(self, m: int, seed: int, epoch: int):
        self.m = m
        bits = 1
        while (1 << bits) < m:
            bits += 1
        self.half_bits = (bits + 1) // 2
        seed64 = seed & 0xFFFFFFFFFFFFFFFF
        self.keys = [
            _mix(((seed64 ^ (seed64 >> 32)) + r * 0x1000193) & 0xFFFFFFFF,
                 ((epoch & 0xFFFFFFFF) * 0x01000193 + 0x811C9DC5 + r) & 0xFFFFFFFF)
            for r in range(4)
        ]

    def __call__(self, x: int) -> int:
        if self.m <= 1:
            return 0
        mask = (1 << self.half_bits) - 1
        while True:
            left, right = x >> self.half_bits, x & mask
            for key in self.keys:
                left, right = right, left ^ (_mix(right & 0xFFFFFFFF, key) & mask)
            x = (left << self.half_bits) | right
            if x < self.m:
                return x


# ---------------------------------------------------------------------------
# dataset + loader
# ---------------------------------------------------------------------------

def write_records(path: str, array: np.ndarray) -> None:
    """Persist [n, ...] array as flat fixed-size records (C-contiguous)."""
    np.ascontiguousarray(array).tofile(path)


class FixedRecordDataset:
    """mmap'd flat file of fixed-size records."""

    def __init__(self, path: str, record_shape: Sequence[int], dtype=np.int32):
        self.path = str(path)
        self.record_shape = tuple(record_shape)
        self.dtype = np.dtype(dtype)
        self.record_bytes = int(np.prod(self.record_shape)) * self.dtype.itemsize
        size = os.path.getsize(self.path)
        if size == 0 or size % self.record_bytes != 0:
            raise ValueError(
                f"{path}: size {size} is not a multiple of record "
                f"size {self.record_bytes}")
        self.n_records = size // self.record_bytes


class DataLoader:
    """Deterministic, shard-aware, prefetching batch iterator.

    Native path: C++ worker threads assemble batches off-thread and the
    Python side copies each ready batch out of the bounded queue. Fallback
    path: same permutation evaluated in Python over a np.memmap. Both yield
    bit-identical batch streams for a given (seed, shard, num_shards).
    """

    def __init__(self, dataset: FixedRecordDataset, batch_size: int,
                 shard_id: int = 0, num_shards: int = 1, seed: int = 0,
                 shuffle: bool = True, num_workers: int = 2,
                 prefetch: int = 4, force_python: bool = False,
                 start_batch: int = 0):
        if start_batch < 0:
            raise ValueError(f"start_batch must be >= 0, got {start_batch}")
        self.ds = dataset
        self.batch_size = batch_size
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.seed = seed
        self.shuffle = shuffle
        self.per_shard = dataset.n_records // num_shards
        if self.per_shard < batch_size:
            raise ValueError(
                f"shard has {self.per_shard} records < batch {batch_size}")
        self.batches_per_epoch = self.per_shard // batch_size
        # tickets are absolute (epoch = ticket // batches_per_epoch), so a
        # checkpointed position resumes the exact stream in O(1) — the
        # data loop replays nothing and skips nothing after preemption
        self._ticket = start_batch
        self._native = None
        self._handle = None
        lib = None if force_python else _get_lib()
        if lib is not None:
            handle = lib.tk_open(dataset.path.encode(), dataset.record_bytes)
            if handle:
                loader = lib.tk_loader_start(
                    handle, batch_size, shard_id, num_shards, seed,
                    start_batch, 1 if shuffle else 0, num_workers,
                    prefetch)
                if loader:
                    self._native = lib
                    self._handle = handle
                    self._loader = loader
        if self._native is None:
            self._mm = np.memmap(dataset.path, dtype=self.ds.dtype, mode="r")
            self._mm = self._mm.reshape(dataset.n_records, -1)

    @property
    def is_native(self) -> bool:
        return self._native is not None

    def _next_python(self) -> np.ndarray:
        epoch = self._ticket // self.batches_per_epoch
        batch_idx = self._ticket % self.batches_per_epoch
        perm = _Feistel(self.per_shard, self.seed, epoch)
        out = np.empty((self.batch_size,) + self.ds.record_shape, self.ds.dtype)
        flat = out.reshape(self.batch_size, -1)
        for j in range(self.batch_size):
            local = batch_idx * self.batch_size + j
            if self.shuffle:
                local = perm(local)
            flat[j] = self._mm[local * self.num_shards + self.shard_id]
        return out

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        if self._native is not None:
            out = np.empty((self.batch_size,) + self.ds.record_shape,
                           self.ds.dtype)
            ok = self._native.tk_next(
                self._loader, out.ctypes.data_as(ctypes.c_char_p))
            if not ok:
                # loader stopped (concurrent close()) — the buffer was never
                # written; surfacing it as a batch would be garbage data
                raise StopIteration
        else:
            out = self._next_python()
        self._ticket += 1
        return out

    def state(self) -> dict:
        """Checkpointable position + stream identity; restore with
        ``DataLoader.resume(dataset, state)`` (which validates the
        identity so a mismatched restore fails loudly)."""
        return {"ticket": self._ticket, "seed": self.seed,
                "shard_id": self.shard_id, "num_shards": self.num_shards,
                "batch_size": self.batch_size, "shuffle": self.shuffle,
                "n_records": self.ds.n_records}

    @classmethod
    def resume(cls, dataset: FixedRecordDataset, state: dict,
               **kwargs) -> "DataLoader":
        """A loader continuing the exact stream a ``state()`` snapshot
        recorded. The identity fields (seed/shard/batch size) come FROM
        the state; overriding them with different values raises — a
        silent mismatch would resume a different permutation and corrupt
        the training stream."""
        if ("n_records" in state
                and dataset.n_records != state["n_records"]):
            # a re-packed/grown corpus changes the permutation domain —
            # every batch from the ticket on would silently differ
            raise ValueError(
                f"dataset has {dataset.n_records} records but the "
                f"checkpoint recorded {state['n_records']}")
        for k in ("seed", "shard_id", "num_shards", "batch_size",
                  "shuffle"):
            if k in kwargs and kwargs[k] != state[k]:
                raise ValueError(
                    f"resume {k}={kwargs[k]} contradicts the checkpointed "
                    f"{k}={state[k]}")
            kwargs[k] = state[k]
        return cls(dataset, start_batch=state["ticket"], **kwargs)

    def close(self) -> None:
        if self._native is not None:
            self._native.tk_loader_stop(self._loader)
            self._native.tk_close(self._handle)
            self._native = None

    def __del__(self):
        try:
            self.close()
        # analyze: allow[silent-loss] __del__ at interpreter teardown — raising would print unraisable noise over a closed stream
        except Exception:
            pass
